"""Compile-cache warm-up + size-bounded GC for `.jax_cache`.

Deep pairing kernels compile in minutes (CPU backend: 7-13 min for the
sharded grouped kernel); a cold cache at the wrong moment costs a restart
its first slots — or a driver dry-run its timeout (round-4 lesson:
`MULTICHIP_r04.json` went red purely on a cold-cache compile). This tool
makes warm-up an explicit, documented step:

  python tools/warmup.py                 # production ladder, current platform
  python tools/warmup.py --dryrun        # the driver's dryrun_multichip(8)
                                         #   CPU-mesh shape (run after the
                                         #   LAST kernel change of a round)
  python tools/warmup.py --prune-gb 6    # GC the cache down to 6 GiB (LRU)
  python tools/warmup.py --aot-export    # producer mode: additionally
                                         #   serialize every compiled
                                         #   executable into the AOT store
                                         #   (restart without XLA — ISSUE 19)

Every warm-up pass ends with an automatic LRU GC of the cache (bound:
LODESTAR_TPU_CACHE_LIMIT_GB, default 2 GiB) — the policy lives in
tools/prune_compile_cache.py, which is also a standalone CLI.

The production ladder = every shape the buffered verifier can dispatch
steady-state: per-set buckets (4, 16, 64, 128) + grouped configs
(16x8, 64x64) + the pk-grouped config (128x32 — the adversarial
unique-root flood defense routes here) + the bisection-verdict tree
kernel per bucket and its fixed-shape probe kernel (the per-set verdict
path, round 6) + the standalone batched final exp and — when
LODESTAR_TPU_PALLAS_MILLER / LODESTAR_TPU_PALLAS_PAIRING resolve on —
the Pallas Miller tower (ISSUE 14) and the fused full-pairing kernel
(ISSUE 18) + the epoch-table gather kernel + the bench shapes when
--bench is given. Device
decompression is DEFAULT-ON (round 6), so the *_raw kernel variants —
on-chip signature decode + subgroup checks — are warmed for the same
shapes by default; LODESTAR_TPU_DEVICE_DECOMPRESS=0 (or
--no-device-decompress) skips them for hosts that pin the C-tier
marshal. Reference analog: the reference avoids this class of problem
by having no compile step at all (blst is AOT); on TPU the restart
story is "run warmup.py once per binary/kernel revision"
(docs/architecture.md §compile-cache). The cache location honors
LODESTAR_TPU_COMPILE_CACHE (utils/jax_env.enable_compile_cache) like
node.py and bench.py.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

CACHE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache")
)


def prune_cache(limit_gb: float | None = None) -> None:
    """LRU-GC the cache to the bound (tools/prune_compile_cache.py owns
    the policy; default bound 2 GiB, LODESTAR_TPU_CACHE_LIMIT_GB
    overrides). XLA cache entries are independent files — deleting one
    only costs a recompile of that one kernel."""
    import prune_compile_cache

    if limit_gb is None:
        limit_gb = prune_compile_cache.default_limit_gb()
    result = prune_compile_cache.prune(CACHE_DIR, limit_gb)
    print(f"cache: {result['entries']} entries (bound {limit_gb} GiB); "
          f"pruned {len(result['removed'])} -> "
          f"{result['total_bytes'] / (1 << 30):.2f} GiB")


def warm_production(include_bench: bool, device_decompress: bool = True) -> None:
    """Compile the production dispatch ladder on the current platform
    (TPU when available — run this at deploy; each shape is one cached
    XLA executable). `device_decompress` (default-on, matching the
    runtime default) adds the *_raw kernel variants (on-chip signature
    decode) for every shape in the ladder."""
    from lodestar_tpu.observability.compile_ledger import ledger, timeline
    from lodestar_tpu.utils.jax_env import enable_compile_cache

    enable_compile_cache(CACHE_DIR)
    timeline().mark("warmup_start")
    # objectives loaded before any kernel warms: the cold-start table
    # gains an `slo_ready` column, and burn state covers the whole
    # warmup ladder (a wedged compile shows as serving_ready burning)
    from lodestar_tpu.observability import slo
    from lodestar_tpu.observability.stages import default_pipeline

    slo.install(default_pipeline())
    timeline().mark("slo_ready")
    import jax

    from __graft_entry__ import (
        _example_arrays,
        _example_grouped,
        _example_pk_grouped,
    )
    from lodestar_tpu.parallel.mesh import mesh_divisor
    from lodestar_tpu.parallel.verifier import BatchVerifier, SetArrays

    buckets = (4, 16, 64, 128) + ((4096,) if include_bench else ())
    grouped = ((16, 8), (64, 64)) + (
        ((64, 256), (64, 512), (64, 1024)) if include_bench else ()
    )
    # the pk-grouped dual-axis config: the planner's default
    # (parallel/verifier pk_grouped_configs) — an adversarial unique-root
    # flood routes production batches here, so a cold compile at that
    # moment is exactly the missed-slots failure this tool prevents
    pk_grouped = ((128, 32),)
    bv = BatchVerifier(
        buckets=buckets, grouped_configs=grouped, pk_grouped_configs=pk_grouped
    )
    for b in buckets:
        arrs = SetArrays(b)
        (arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
         arrs.sig_x, arrs.sig_y, r_bits, arrs.valid) = _example_arrays(b)
        arrs.n = b
        t0 = time.monotonic()
        ok = bool(bv.verify_batch(arrs, r_bits))
        print(f"per-set bucket {b}: {time.monotonic() - t0:.1f}s verdict={ok}",
              flush=True)
        t0 = time.monotonic()
        ok = bv.verify_individual(arrs)
        jax.block_until_ready(ok)
        print(f"individual bucket {b}: {time.monotonic() - t0:.1f}s", flush=True)
        # the bisection-verdict tree (the per-set verdict path's common
        # case — ONE final exp) per PRODUCTION bucket; a cold compile
        # here would hit exactly when a batch just failed and verdicts
        # are urgent. The bench-only 4096 bucket is skipped: the verdict
        # path never dispatches it (bench's bisect phase runs at 128).
        if b <= 128:
            t0 = time.monotonic()
            root_ok, _levels = bv.verify_bisect_tree(arrs, r_bits)
            jax.block_until_ready(root_ok)
            print(f"bisect tree bucket {b}: {time.monotonic() - t0:.1f}s "
                  f"root_ok={bool(root_ok)}", flush=True)
        timeline().mark(f"rung_bucket_{b}")
    # the fixed-shape bisection probe kernel (ONE compile total)
    import numpy as np
    from lodestar_tpu.ops import fp12 as _fp12
    from lodestar_tpu.parallel.verifier import PROBE_LANES

    t0 = time.monotonic()
    probe = bv.probe_nodes(np.asarray(_fp12.one((PROBE_LANES,))))
    jax.block_until_ready(probe)
    print(f"bisect probe x{PROBE_LANES}: {time.monotonic() - t0:.1f}s",
          flush=True)
    # the standalone shared-inversion batched final exp (ISSUE 14): the
    # bench floor comparison and /debug/compiles entry for the batched-FE
    # tail every verdict kernel inlines
    t0 = time.monotonic()
    fe = bv.final_exp_batch(np.asarray(_fp12.one((PROBE_LANES,))))
    jax.block_until_ready(fe)
    print(f"final exp batch x{PROBE_LANES}: {time.monotonic() - t0:.1f}s",
          flush=True)
    timeline().mark("rung_final_exp_batch")
    # the VMEM-resident Pallas Miller tower: warmed only when the
    # LODESTAR_TPU_PALLAS_MILLER knob resolves on (TPU deploys; the CPU
    # interpreter path is a differential-test vehicle, not a serving
    # shape worth a warmup rung)
    from lodestar_tpu.ops import pallas_tower

    if pallas_tower.enabled():
        arrs = SetArrays(buckets[0])
        (arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
         arrs.sig_x, arrs.sig_y, _r_bits, arrs.valid) = _example_arrays(
            buckets[0]
        )
        t0 = time.monotonic()
        out = bv.miller_pallas(
            (arrs.pk_x, arrs.pk_y), (arrs.msg_x, arrs.msg_y)
        )
        jax.block_until_ready(out)
        print(f"miller pallas x{buckets[0]}: {time.monotonic() - t0:.1f}s",
              flush=True)
        timeline().mark("rung_miller_pallas")
    # the fused full-pairing kernel (ISSUE 18): same gating logic — on
    # TPU deploys the per-set verdict path routes here, so its compile
    # belongs in the ladder; the CPU interpreter path stays a
    # differential-test vehicle
    if pallas_tower.pairing_enabled():
        arrs = SetArrays(buckets[0])
        (arrs.pk_x, arrs.pk_y, arrs.msg_x, arrs.msg_y,
         arrs.sig_x, arrs.sig_y, _r_bits, arrs.valid) = _example_arrays(
            buckets[0]
        )
        arrs.n = buckets[0]
        t0 = time.monotonic()
        out = bv.pairing_pallas(arrs)
        jax.block_until_ready(out)
        print(f"pairing pallas x{buckets[0]}: {time.monotonic() - t0:.1f}s",
              flush=True)
        timeline().mark("rung_pairing_pallas")
    # the epoch-table gather kernel (ISSUE 18): one tiny compile that
    # otherwise lands on the first post-restart epoch transition
    from lodestar_tpu.parallel.epoch_table import ROW_WIDTH, EpochPubkeyTable

    table = EpochPubkeyTable(epochs=1, max_rows=8)
    table.populate(0, [(bytes([i]) * 48, np.zeros(ROW_WIDTH, np.int32))
                       for i in range(4)])
    t0 = time.monotonic()
    gathered = table.gather_device(0, np.arange(4))
    if gathered is not None:
        jax.block_until_ready(gathered)
    print(f"epoch table gather x4: {time.monotonic() - t0:.1f}s "
          f"device={gathered is not None}", flush=True)
    timeline().mark("rung_epoch_table")
    for rows, lanes in grouped:
        if device_decompress:
            g, a_bits, b_bits, sig_raw = _example_grouped(rows, lanes, raw=True)
        else:
            g, a_bits, b_bits = _example_grouped(rows, lanes)
        t0 = time.monotonic()
        ok = bool(bv.verify_grouped(g, a_bits, b_bits))
        print(f"grouped {rows}x{lanes}: {time.monotonic() - t0:.1f}s "
              f"verdict={ok}", flush=True)
        if device_decompress:
            t0 = time.monotonic()
            ok = bool(bv.verify_grouped_raw(g, sig_raw, a_bits, b_bits))
            print(f"grouped raw {rows}x{lanes}: {time.monotonic() - t0:.1f}s "
                  f"verdict={ok}", flush=True)
        timeline().mark(f"rung_grouped_{rows}x{lanes}")
    for rows, lanes in pk_grouped:
        if device_decompress:
            g, a_bits, b_bits, sig_raw = _example_pk_grouped(
                rows, lanes, raw=True
            )
        else:
            g, a_bits, b_bits = _example_pk_grouped(rows, lanes)
        t0 = time.monotonic()
        ok = bool(bv.verify_pk_grouped(g, a_bits, b_bits))
        print(f"pk-grouped {rows}x{lanes}: {time.monotonic() - t0:.1f}s "
              f"verdict={ok}", flush=True)
        if device_decompress:
            t0 = time.monotonic()
            ok = bool(bv.verify_pk_grouped_raw(g, sig_raw, a_bits, b_bits))
            print(f"pk-grouped raw {rows}x{lanes}: "
                  f"{time.monotonic() - t0:.1f}s verdict={ok}", flush=True)
        timeline().mark(f"rung_pk_grouped_{rows}x{lanes}")
    # sharded-raw ladder (ISSUE 15): with >1 visible device the mesh
    # dispatcher routes raw gossip bytes to the on-mesh decompression
    # twins by default — warm them for every production grouped shape the
    # mesh can shard (rows divisible by the mesh size), or a cold compile
    # lands on the first gossip batch after a restart
    n_mesh = mesh_divisor(len(jax.devices()))
    if device_decompress and n_mesh >= 2:
        from jax.sharding import Mesh

        from lodestar_tpu.parallel.sharded import (
            ShardedGroupedRawVerifier,
            ShardedPkGroupedRawVerifier,
        )

        mesh = Mesh(np.array(jax.devices()[:n_mesh]), axis_names=("dp",))
        sgr = ShardedGroupedRawVerifier(mesh)
        for rows, lanes in grouped:
            if rows % n_mesh:
                continue
            g, a_bits, b_bits, sig_raw = _example_grouped(rows, lanes, raw=True)
            t0 = time.monotonic()
            ok = bool(sgr.submit(g, sig_raw, a_bits, b_bits))
            print(f"sharded-raw grouped {rows}x{lanes} /{n_mesh}: "
                  f"{time.monotonic() - t0:.1f}s verdict={ok}", flush=True)
            timeline().mark(f"rung_sharded_raw_{rows}x{lanes}")
        spgr = ShardedPkGroupedRawVerifier(mesh)
        for rows, lanes in pk_grouped:
            if rows % n_mesh:
                continue
            g, a_bits, b_bits, sig_raw = _example_pk_grouped(
                rows, lanes, raw=True
            )
            t0 = time.monotonic()
            ok = bool(spgr.submit(g, sig_raw, a_bits, b_bits))
            print(f"sharded-raw pk-grouped {rows}x{lanes} /{n_mesh}: "
                  f"{time.monotonic() - t0:.1f}s verdict={ok}", flush=True)
            timeline().mark(f"rung_sharded_raw_pk_{rows}x{lanes}")
    # fleet two-level twins (ISSUE 20): when a fleet topology is active
    # (LODESTAR_TPU_FLEET), the mesh dispatcher serves from a (dcn, ici)
    # two-level shard_map — a DIFFERENT executable per host count than
    # the flat single-host twins above, recorded under the fleet_*
    # kernel names. Warm them through the dispatcher itself so the
    # compile-ledger wrap (and --aot-export, which rides the ledger's
    # AOT seam) covers exactly the production dispatch path.
    from lodestar_tpu.parallel.fleet import FleetTopology

    topo = FleetTopology.from_env()
    host_rows = topo.group_devices(jax.devices()) if topo.active else None
    if host_rows is not None:
        from lodestar_tpu.parallel.mesh import NOT_SHARDED, BlsMeshDispatcher

        disp = BlsMeshDispatcher(jax.devices(), hosts=host_rows)
        if disp.hosts_serving > 1:
            for rows, lanes in grouped:
                if rows % disp.size:
                    continue
                g, a_bits, b_bits, sig_raw = _example_grouped(
                    rows, lanes, raw=True
                )
                t0 = time.monotonic()
                ok = disp.dispatch_grouped(g, a_bits, b_bits)
                if ok is NOT_SHARDED:
                    continue
                print(f"fleet grouped {rows}x{lanes} "
                      f"/{disp.hosts_serving}h: "
                      f"{time.monotonic() - t0:.1f}s verdict={bool(ok)}",
                      flush=True)
                timeline().mark(f"rung_fleet_{rows}x{lanes}")
                if device_decompress:
                    t0 = time.monotonic()
                    ok = disp.dispatch_grouped_raw(g, sig_raw, a_bits, b_bits)
                    if ok is not NOT_SHARDED:
                        print(f"fleet grouped raw {rows}x{lanes} "
                              f"/{disp.hosts_serving}h: "
                              f"{time.monotonic() - t0:.1f}s "
                              f"verdict={bool(ok)}", flush=True)
                        timeline().mark(f"rung_fleet_raw_{rows}x{lanes}")
    # the ladder is the serving contract: every production shape compiled
    # means a node restarting against this cache is serving-ready here
    t_ready = timeline().mark_serving_ready()
    snap = ledger().snapshot()
    print(f"warmup: serving-ready at {t_ready:.1f}s since process start "
          f"({snap['cumulative_seconds']:.1f}s in compiles)",
          flush=True)
    aot = snap.get("aot") or {}
    if aot.get("store") and (aot.get("counts") or aot.get("export")):
        print(f"warmup: aot store {aot['store']}: {aot.get('counts', {})} "
              f"({aot.get('loaded_executables', 0)} executable(s) "
              f"in memory)", flush=True)
    ledger().write_artifact(os.path.join(CACHE_DIR, "..",
                                         "compile_ledger.json"))


def warm_dryrun(n: int) -> None:
    """Warm the exact shape the driver's multichip dry-run compiles (the
    round-4 red-signal failure mode). Must run in a fresh process that
    hasn't touched jax yet — re-exec if a backend already initialized."""
    import __graft_entry__

    t0 = time.monotonic()
    __graft_entry__.dryrun_multichip(n)
    print(f"dryrun_multichip({n}) warm in {time.monotonic() - t0:.1f}s")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dryrun", action="store_true",
                    help="warm the driver's CPU-mesh dryrun shape instead")
    ap.add_argument("--devices", type=int, default=8,
                    help="mesh size for --dryrun")
    ap.add_argument("--bench", action="store_true",
                    help="also warm the bench shapes (4096-set, 64x256/512)")
    ap.add_argument("--device-decompress", action="store_true",
                    help="warm the *_raw kernels (on-chip signature decode; "
                         "DEFAULT since round 6 — kept for compatibility)")
    ap.add_argument("--no-device-decompress", action="store_true",
                    help="skip the *_raw kernels (for hosts pinning the "
                         "C-tier marshal via LODESTAR_TPU_DEVICE_DECOMPRESS=0)")
    ap.add_argument("--aot-export", action="store_true",
                    help="producer mode for the AOT executable store "
                         "(ops/aot_store.py): every ladder compile is "
                         "serialized to LODESTAR_TPU_AOT_STORE so a node "
                         "restart loads machine code instead of entering "
                         "XLA (sets LODESTAR_TPU_AOT_EXPORT=1)")
    ap.add_argument("--prune-gb", type=float, default=None,
                    help="GC the cache to this many GiB (LRU) and exit")
    args = ap.parse_args()
    if args.aot_export:
        # before any jax/ledger work: export_enabled() is read at each
        # kernel's first dispatch
        os.environ["LODESTAR_TPU_AOT_EXPORT"] = "1"
    if args.prune_gb is not None:
        prune_cache(args.prune_gb)
        return
    if args.dryrun:
        warm_dryrun(args.devices)
        prune_cache()  # self-bounding: every warm-up pass ends with GC
        return
    # mirror the runtime default: raw kernels ON unless explicitly off
    # (an explicit --device-decompress wins over the env off-switch)
    from lodestar_tpu.utils.env import env_bool

    env_off = not env_bool("LODESTAR_TPU_DEVICE_DECOMPRESS")
    device_decompress = args.device_decompress or not (
        args.no_device_decompress or env_off
    )
    warm_production(args.bench, device_decompress=device_decompress)
    prune_cache()  # self-bounding: every warm-up pass ends with GC


if __name__ == "__main__":
    main()
