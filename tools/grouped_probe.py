"""Measure the grouped batch-verify kernel on device.

Shapes: (R roots × L lanes) gossip shape — the bench's 64-unique-root
batch (BASELINE config #2). Prints compile time and steady-state sets/s
per config. Run on the TPU (default env) or CPU (JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import os
import sys
import time


sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)


def example_grouped(rows: int, lanes: int):
    """Valid grouped arrays: one signer per root, tiled across lanes."""
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.bls.hash_to_curve import hash_to_g2
    from lodestar_tpu.ops.io_host import g1_affine_to_limbs, g2_affine_to_limbs
    from lodestar_tpu.parallel.verifier import GroupedArrays, _rand_pairs

    g = GroupedArrays(rows, lanes)
    for j in range(rows):
        sk = bls.interop_secret_key(j)
        msg = bytes([j]) * 32
        pkx, pky, _ = g1_affine_to_limbs(sk.to_public_key().point)
        h = hash_to_g2(msg)
        g.msg_x[j], g.msg_y[j], _ = g2_affine_to_limbs(h)
        sx, sy, _ = g2_affine_to_limbs(sk.sign(msg).point)
        g.pk_x[j, :] = pkx
        g.pk_y[j, :] = pky
        g.sig_x[j, :] = sx
        g.sig_y[j, :] = sy
    g.valid[:] = True
    g.n = rows * lanes
    a_bits, b_bits = _rand_pairs((rows, lanes))
    return g, a_bits, b_bits


def probe(rows: int, lanes: int, reps: int = 3):
    from lodestar_tpu.parallel.verifier import grouped_verify_kernel

    g, a_bits, b_bits = example_grouped(rows, lanes)
    args = [
        jax.device_put(a)
        for a in (
            g.pk_x, g.pk_y, g.msg_x, g.msg_y, g.sig_x, g.sig_y,
            a_bits, b_bits, g.valid,
        )
    ]
    jax.block_until_ready(args)
    fn = jax.jit(grouped_verify_kernel)
    t0 = time.perf_counter()
    ok = bool(fn(*args))
    compile_s = time.perf_counter() - t0
    print(f"({rows},{lanes}) compile+first: {compile_s:.1f}s ok={ok}", flush=True)
    assert ok, "valid grouped batch rejected"
    t0 = time.perf_counter()
    for _ in range(reps):
        r = fn(*args)
    r.block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    n = rows * lanes
    print(
        f"({rows},{lanes}) steady: {dt*1e3:.0f} ms -> {n/dt:.1f} sets/s",
        flush=True,
    )
    return n / dt


if __name__ == "__main__":
    shapes = sys.argv[1:] or ["64x64"]
    for s in shapes:
        r, l = (int(v) for v in s.split("x"))
        probe(r, l)
