"""Compare scalar-ladder variants on the current backend (compile + steady)."""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)
import jax.numpy as jnp
import numpy as np

from lodestar_tpu.bls import curve as oc
from lodestar_tpu.ops.io_host import g1_affine_to_limbs, g2_affine_to_limbs
from lodestar_tpu.ops.points import g1, g2

B = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
rng = np.random.default_rng(0)
bits = jnp.asarray(rng.integers(0, 2, (B, 64), dtype=np.int32))

g1x, g1y, _ = g1_affine_to_limbs(oc.PointG1.generator())
g2x, g2y, _ = g2_affine_to_limbs(oc.PointG2.generator())
cases = [
    ("g1 bits", g1.scalar_mul_bits, (jnp.broadcast_to(g1x, (B, 32)), jnp.broadcast_to(g1y, (B, 32)))),
    ("g1 windowed", g1.scalar_mul_windowed, (jnp.broadcast_to(g1x, (B, 32)), jnp.broadcast_to(g1y, (B, 32)))),
    ("g2 bits", g2.scalar_mul_bits, (jnp.broadcast_to(g2x, (B, 2, 32)), jnp.broadcast_to(g2y, (B, 2, 32)))),
    ("g2 windowed", g2.scalar_mul_windowed, (jnp.broadcast_to(g2x, (B, 2, 32)), jnp.broadcast_to(g2y, (B, 2, 32)))),
]
for name, fn, q in cases:
    f = jax.jit(fn)
    t0 = time.perf_counter()
    r = f(bits, q)
    jax.block_until_ready(r)
    t_c = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(3):
        r = f(bits, q)
    jax.block_until_ready(r)
    print(
        f"{name} B={B}: compile+1={t_c:.1f}s steady={(time.perf_counter()-t0)/3*1000:.0f} ms",
        flush=True,
    )
