"""Size-bounded LRU GC for the persistent XLA compile cache + AOT store.

The `.jax_cache` directory only ever grows: every kernel revision, bench
shape and mesh size leaves its executables behind (the sharded grouped
kernel alone serializes ~7 MB per shape, and a round of warmup + bench +
mesh-scaling probes writes dozens of entries). The `.aot_store` of
serialized AOT executables (ISSUE 19) grows the same way and its
artifacts are BIGGER (~40 MB for the grouped kernel on CPU). Entries in
both are independent files — deleting one costs exactly one recompile
(or one re-export) of that kernel — so the right policy is plain LRU by
file age with ONE shared size bound across both directories, the same
shape as the reference's worker-pool keeping `poolSize` bounded rather
than unbounded.

    python tools/prune_compile_cache.py                # bound to 2 GiB
    python tools/prune_compile_cache.py --limit-gb 6   # custom bound
    python tools/prune_compile_cache.py --dry-run      # report only
    python tools/prune_compile_cache.py --no-aot       # .jax_cache only

`tools/warmup.py` invokes `prune(...)` automatically at the end of every
warm-up pass (LODESTAR_TPU_CACHE_LIMIT_GB overrides the 2 GiB default),
so the steady-state workflow — warm, bench, repeat — self-bounds instead
of filling the disk. Recency is `max(atime, mtime)`: atime tracks cache
HITS where the filesystem records it (an entry the node loads every
restart stays — `aot_store.load` additionally utimes on every hit),
mtime is the portable fallback on noatime mounts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_LIMIT_GB = 2.0
ENV_LIMIT = "LODESTAR_TPU_CACHE_LIMIT_GB"
DEFAULT_CACHE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache")
)


def default_limit_gb() -> float:
    """The configured bound: LODESTAR_TPU_CACHE_LIMIT_GB, else 2 GiB."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    )
    from lodestar_tpu.utils.env import env_float

    return env_float(ENV_LIMIT)


def default_aot_dir() -> str | None:
    """The configured AOT store directory sharing the byte budget, or
    None when the store is disabled (LODESTAR_TPU_AOT_STORE=off)."""
    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    )
    try:
        from lodestar_tpu.ops.aot_store import store_dir
    except ImportError:
        return None  # standalone copy outside the repo tree
    return store_dir()


def scan(cache_dir: str) -> list[tuple[float, int, str]]:
    """[(recency, size, path)] for every regular file in the cache —
    oldest first. Missing directory scans as empty (a fresh checkout has
    no cache yet; pruning it is a no-op, not an error)."""
    entries = []
    try:
        names = os.listdir(cache_dir)
    except FileNotFoundError:
        return []
    for name in names:
        path = os.path.join(cache_dir, name)
        if not os.path.isfile(path):
            continue
        st = os.stat(path)
        entries.append((max(st.st_atime, st.st_mtime), st.st_size, path))
    entries.sort()
    return entries


_AOT_AUTO = object()  # sentinel: resolve the AOT dir from the env registry


def prune(
    cache_dir: str = DEFAULT_CACHE_DIR,
    limit_gb: float | None = None,
    dry_run: bool = False,
    aot_dir=_AOT_AUTO,
) -> dict:
    """Delete least-recently-used entries until the cache fits the bound.

    The bound is SHARED across the XLA trace cache and the AOT executable
    store (ISSUE 19): both directories' entries compete in one LRU order,
    so a rarely-restarted shape's 40 MB AOT artifact is evicted before a
    hot trace-cache entry. `aot_dir` defaults to the env-configured store
    (None = cache dir only).

    Returns {"entries", "entries_remaining", "total_bytes",
    "limit_bytes", "removed", "removed_bytes", "dirs", "aot_removed"} —
    `removed` lists the pruned paths (would-be-pruned under `dry_run`).
    A real (non-dry) prune is observable: a structured
    `compile_cache_prune` log line on stderr and a `note_prune` into the
    compile ledger (metrics when a registry is live, artifact record
    always)."""
    if limit_gb is None:
        limit_gb = default_limit_gb()
    if aot_dir is _AOT_AUTO:
        aot_dir = default_aot_dir()
    dirs = [cache_dir]
    if aot_dir and os.path.abspath(aot_dir) != os.path.abspath(cache_dir):
        dirs.append(aot_dir)
    entries = []
    for d in dirs:
        entries.extend(scan(d))
    entries.sort()
    total = sum(size for _, size, _ in entries)
    limit = int(limit_gb * (1 << 30))
    removed: list[str] = []
    removed_bytes = 0
    if total > limit:
        for _, size, path in entries:
            if not dry_run:
                try:
                    os.unlink(path)
                except FileNotFoundError:
                    continue  # concurrent writer already replaced it
            removed.append(path)
            removed_bytes += size
            total -= size
            if total <= limit:
                break
    aot_prefix = os.path.abspath(aot_dir) + os.sep if aot_dir else None
    result = {
        "entries": len(entries),
        "entries_remaining": len(entries) - len(removed),
        "total_bytes": total,
        "limit_bytes": limit,
        "removed": removed,
        "removed_bytes": removed_bytes,
        "dirs": dirs,
        "aot_removed": (
            sum(1 for p in removed
                if os.path.abspath(p).startswith(aot_prefix))
            if aot_prefix else 0
        ),
    }
    if not dry_run:
        _observe(result)
    return result


def _observe(result: dict) -> None:
    """Make the prune observable: one structured JSON log line on stderr
    (always — grep-able even when nothing else is wired), plus the
    compile ledger's `note_prune`, which persists the record into the
    next `compile_ledger.json` artifact and ticks
    `lodestar_tpu_compile_cache_pruned_bytes_total` /
    `lodestar_tpu_compile_cache_entries` on every live metrics
    pipeline."""
    print(
        json.dumps({
            "event": "compile_cache_prune",
            "entries": result["entries"],
            "entries_remaining": result["entries_remaining"],
            "removed": len(result["removed"]),
            "removed_bytes": result["removed_bytes"],
            "total_bytes": result["total_bytes"],
            "dirs": result.get("dirs"),
            "aot_removed": result.get("aot_removed", 0),
        }),
        file=sys.stderr,
        flush=True,
    )
    try:
        sys.path.insert(
            0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
        )
        from lodestar_tpu.observability.compile_ledger import ledger
    except ImportError:
        return  # standalone copy outside the repo tree
    ledger().note_prune(result)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cache-dir", default=DEFAULT_CACHE_DIR,
                    help="compile-cache directory (default: repo .jax_cache)")
    ap.add_argument("--limit-gb", type=float, default=None,
                    help=f"size bound in GiB (default: ${ENV_LIMIT} or "
                         f"{DEFAULT_LIMIT_GB})")
    ap.add_argument("--dry-run", action="store_true",
                    help="report what would be pruned without deleting")
    ap.add_argument("--aot-dir", default=None,
                    help="AOT executable store sharing the byte budget "
                         "(default: the LODESTAR_TPU_AOT_STORE dir)")
    ap.add_argument("--no-aot", action="store_true",
                    help="bound the XLA trace cache only")
    args = ap.parse_args(argv)
    limit_gb = args.limit_gb if args.limit_gb is not None else default_limit_gb()
    aot_dir = None if args.no_aot else (args.aot_dir or _AOT_AUTO)
    result = prune(args.cache_dir, limit_gb, dry_run=args.dry_run,
                   aot_dir=aot_dir)
    verb = "would prune" if args.dry_run else "pruned"
    print(
        f"cache {' + '.join(result['dirs'])}: {result['entries']} entries, "
        f"bound {limit_gb} GiB; {verb} {len(result['removed'])} "
        f"entries ({result['removed_bytes'] / (1 << 30):.2f} GiB, "
        f"{result['aot_removed']} aot) -> "
        f"{result['total_bytes'] / (1 << 30):.2f} GiB"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
