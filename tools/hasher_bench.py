"""Measure incremental state hashing at mainnet scale (BASELINE Missing #2).

Builds 1M-validator flat columns directly (no SSZ object graph — the
columnar hasher never walks one) and times:
  1. first full build of the validators+balances trees,
  2. re-hash after ONE balance change (the O(log n) path),
  3. re-hash after one epoch-shaped sweep (every effective_balance row
     touched — the worst realistic case).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from lodestar_tpu.state_transition.hasher import _ValidatorsHasher, _u64_chunks
from lodestar_tpu.ssz.tree_cache import ChunkTree
from lodestar_tpu.ssz.hashing import mix_in_length

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
REGISTRY_LIMIT = 1 << 40


class _Cols:
    pass


def main():
    rng = np.random.default_rng(1)
    flat = _Cols()
    flat.pubkeys = [bytes([i % 251, (i >> 8) % 251]) + b"\x22" * 46 for i in range(N)]
    flat.effective_balance = np.full(N, 32_000_000_000, np.uint64)
    flat.slashed = np.zeros(N, bool)
    flat.activation_eligibility_epoch = np.zeros(N, np.uint64)
    flat.activation_epoch = np.zeros(N, np.uint64)
    flat.exit_epoch = np.full(N, (1 << 64) - 1, np.uint64)
    flat.withdrawable_epoch = np.full(N, (1 << 64) - 1, np.uint64)
    flat.withdrawal_credentials = rng.integers(
        0, 256, size=(N, 32), dtype=np.int64
    ).astype(np.uint8)
    balances = rng.integers(31_000_000_000, 33_000_000_000, size=N, dtype=np.uint64)

    class FlatLike:
        withdrawal_credentials = flat.withdrawal_credentials
        pubkeys = flat.pubkeys
        effective_balance = flat.effective_balance
        slashed = flat.slashed
        activation_eligibility_epoch = flat.activation_eligibility_epoch
        activation_epoch = flat.activation_epoch
        exit_epoch = flat.exit_epoch
        withdrawable_epoch = flat.withdrawable_epoch

        def __len__(self):
            return N

    fl = FlatLike()
    vh = _ValidatorsHasher(REGISTRY_LIMIT)
    bt = ChunkTree((REGISTRY_LIMIT + 3) // 4)

    t0 = time.perf_counter()
    r0 = vh.root(fl)
    bt.update(_u64_chunks(balances))
    b0 = mix_in_length(bt.root(), N)
    t_full = time.perf_counter() - t0
    print(f"full build ({N} validators): {t_full:.2f}s")

    balances[N // 2] += 1
    t0 = time.perf_counter()
    r1 = vh.root(fl)
    bt.update(_u64_chunks(balances))
    b1 = mix_in_length(bt.root(), N)
    t_one = time.perf_counter() - t0
    assert r1 == r0 and b1 != b0
    print(f"one balance change: {t_one*1e3:.1f} ms")

    flat.effective_balance[:] = rng.integers(
        31_000_000_000, 33_000_000_000, size=N, dtype=np.uint64
    ) // 1_000_000_000 * 1_000_000_000
    t0 = time.perf_counter()
    vh.root(fl)
    t_sweep = time.perf_counter() - t0
    print(f"all-effective-balance sweep: {t_sweep:.2f}s")
    import json

    print(json.dumps({
        "n_validators": N,
        "full_build_s": round(t_full, 3),
        "one_change_ms": round(t_one * 1e3, 2),
        "epoch_sweep_s": round(t_sweep, 3),
    }))


if __name__ == "__main__":
    main()
