"""Fleet dryrun: prove the two-level (DCN x ICI) mesh is bit-identical
to the single-level mesh, and that subnet-sharded ingest partitions the
work without loss (ISSUE 20 acceptance artifact -> FLEET_r01.json).

Two phases, both on virtual CPU devices (no TPU needed — the point is
the COLLECTIVE LAYOUT and the ROUTING, not silicon):

  mesh_parity    in-process: the same grouped batches (valid + one
                 tampered lane) dispatched through a 1-host x 4-chip
                 flat mesh AND a 2-host x 2-chip two-level mesh; the
                 verdict bytes must be identical, and the fleet census
                 must attribute dispatches to both host rows.

  ingest_wiring  multi-process: two subprocesses, each acting as one
                 fleet host over its FleetRouter subnet slice of a
                 deterministic 64-subnet attestation workload (one
                 valid + one tampered set per subnet, verified with the
                 pure-CPU bls oracle). The merged verdict map must be
                 disjoint, covering, and equal to a single-host run of
                 the full workload.

Usage:
    python tools/dryrun_fleet.py [--out FLEET_r01.json]
    python tools/dryrun_fleet.py --host-rank R --hosts N   (subprocess)

The --host-rank form is the per-host worker the parent spawns; it prints
its slice verdicts as JSON on stdout and must stay jax-free (router +
CPU oracle only) so the wiring phase runs in seconds.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

SUBNETS = 64


# -- deterministic per-subnet workload (shared by parent and workers) ------

def _subnet_sets(subnet: int):
    """One valid and one tampered signature set, derived only from the
    subnet number — every process computes the identical workload."""
    from lodestar_tpu.bls import api as bls

    sk = bls.interop_secret_key(subnet + 1)
    msg = bytes([subnet]) * 32
    good = bls.SignatureSet(
        pubkey=sk.to_public_key(), message=msg, signature=sk.sign(msg).to_bytes()
    )
    wrong = bls.interop_secret_key(997)
    bad = bls.SignatureSet(
        pubkey=sk.to_public_key(),
        message=msg,
        signature=wrong.sign(msg).to_bytes(),
    )
    return good, bad


def _host_worker(rank: int, hosts: int) -> dict:
    """One fleet host: verify only the subnets this rank owns."""
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.parallel.fleet import FleetRouter

    router = FleetRouter(hosts, rank)
    verdicts: dict[str, dict] = {}
    dispatches = 0
    for subnet in range(SUBNETS):
        if not router.owns(subnet):
            router.record_foreign(subnet)
            continue
        good, bad = _subnet_sets(subnet)
        verdicts[str(subnet)] = {
            "valid": bool(bls.verify_signature_sets([good])),
            "tampered": bool(bls.verify_signature_sets([bad])),
        }
        dispatches += 2
    return {
        "rank": rank,
        "owned": len(verdicts),
        "dispatches": dispatches,
        "foreign_dropped": router.snapshot()["foreign_dropped"],
        "verdicts": verdicts,
    }


# -- phase 1: two-level mesh verdict parity --------------------------------

def _mesh_parity() -> dict:
    from lodestar_tpu.utils.jax_env import force_platform

    force_platform("cpu", 4)

    import jax
    import numpy as np

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                     ".jax_cache"),
    )

    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.parallel.fleet import FleetRouter
    from lodestar_tpu.parallel.mesh import NOT_SHARDED, BlsMeshDispatcher
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier, _rand_pairs

    host = TpuBlsVerifier(buckets=(16,), grouped_configs=((8, 4),))

    def make_sets(tamper_idx=None):
        sets = []
        for i in range(16):
            sk = bls.interop_secret_key(i + 1)
            root = b"\x42" * 32 if i < 8 else b"\x43" * 32
            signer = sk if i != tamper_idx else bls.interop_secret_key(99)
            sets.append(
                bls.SignatureSet(
                    pubkey=sk.to_public_key(),
                    message=root,
                    signature=signer.sign(root).to_bytes(),
                )
            )
        return sets

    def marshal(sets):
        plan = host._plan_groups(sets)
        g = host._marshal_grouped(sets, plan)
        assert g is not None, "grouped marshal refused the dryrun batch"
        return g

    devices = jax.devices("cpu")[:4]
    flat = BlsMeshDispatcher(devices)
    fleet = BlsMeshDispatcher(
        devices, hosts=[[0, 1], [2, 3]], router=FleetRouter(2, 0)
    )
    assert flat.size == 4 and fleet.size == 4 and fleet.hosts_serving == 2

    counter = [0]

    def rng():
        counter[0] += 1
        return (0x9E3779B97F4A7C15 * counter[0]) & ((1 << 64) - 1)

    g_good = marshal(make_sets())
    g_bad = marshal(make_sets(tamper_idx=3))
    a_bits, b_bits = _rand_pairs(g_good.valid.shape, rng)

    cases = {}
    t0 = time.monotonic()
    for label, g in (("valid", g_good), ("tampered", g_bad)):
        # the single-device truth is pinned by the asserts on the flat
        # verdicts below (valid accepts, tampered rejects) — no third
        # kernel compile; this box has one core and deep pairing
        # compiles cost minutes each
        v_flat = flat.dispatch_grouped(g, a_bits, b_bits)
        v_fleet = fleet.dispatch_grouped(g, a_bits, b_bits)
        assert v_flat is not NOT_SHARDED and v_fleet is not NOT_SHARDED
        flat_bytes = np.asarray(v_flat).tobytes().hex()
        fleet_bytes = np.asarray(v_fleet).tobytes().hex()
        cases[label] = {
            "flat_verdict": bool(v_flat),
            "fleet_verdict": bool(v_fleet),
            "flat_bytes": flat_bytes,
            "fleet_bytes": fleet_bytes,
            "bit_identical": flat_bytes == fleet_bytes,
        }
        print(f"mesh_parity[{label}]: flat={bool(v_flat)} "
              f"fleet={bool(v_fleet)} identical="
              f"{flat_bytes == fleet_bytes}", flush=True)
    elapsed = round(time.monotonic() - t0, 3)

    snap = fleet.fleet_snapshot()
    parity_ok = (
        cases["valid"]["flat_verdict"] is True
        and cases["tampered"]["flat_verdict"] is False
        and all(c["bit_identical"] for c in cases.values())
    )
    return {
        "devices": 4,
        "layouts": {"flat": "1x4 (dp)", "fleet": "2x2 (dcn,ici)"},
        "cases": cases,
        "parity_ok": parity_ok,
        "elapsed_s": elapsed,
        "fleet_census": snap,
    }


# -- phase 2: multi-process subnet-sharded ingest --------------------------

def _ingest_wiring() -> dict:
    from lodestar_tpu.bls import api as bls

    me = os.path.abspath(__file__)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, me, "--host-rank", str(r), "--hosts", "2"],
            stdout=subprocess.PIPE,
            env=env,
        )
        for r in range(2)
    ]
    outs = []
    for p in procs:
        raw, _ = p.communicate(timeout=600)
        assert p.returncode == 0, f"host worker rc={p.returncode}"
        outs.append(json.loads(raw))

    merged: dict[str, dict] = {}
    for doc in outs:
        for subnet, verdict in doc["verdicts"].items():
            assert subnet not in merged, f"subnet {subnet} owned twice"
            merged[subnet] = verdict
    assert len(merged) == SUBNETS, f"coverage hole: {len(merged)}/{SUBNETS}"

    # single-host reference: the same workload with no router filtering
    reference = {}
    for subnet in range(SUBNETS):
        good, bad = _subnet_sets(subnet)
        reference[str(subnet)] = {
            "valid": bool(bls.verify_signature_sets([good])),
            "tampered": bool(bls.verify_signature_sets([bad])),
        }
    parity_ok = merged == reference
    return {
        "hosts": 2,
        "per_host": [
            {k: doc[k] for k in
             ("rank", "owned", "dispatches", "foreign_dropped")}
            for doc in outs
        ],
        "subnets_covered": len(merged),
        "disjoint": True,  # asserted above
        "parity_ok": parity_ok,
        "all_valid_accepted": all(v["valid"] for v in merged.values()),
        "all_tampered_rejected": not any(
            v["tampered"] for v in merged.values()
        ),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="write the dryrun document here (default stdout)")
    ap.add_argument("--host-rank", type=int, default=None,
                    help="internal: run as one fleet host worker")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--skip-mesh", action="store_true",
                    help="skip the jax mesh-parity phase (wiring only)")
    args = ap.parse_args()

    if args.host_rank is not None:
        json.dump(_host_worker(args.host_rank, args.hosts), sys.stdout)
        return 0

    doc = {"artifact": "FLEET_r01", "subnet_count": SUBNETS}
    doc["ingest_wiring"] = _ingest_wiring()
    if not args.skip_mesh:
        doc["mesh_parity"] = _mesh_parity()
    ok = doc["ingest_wiring"]["parity_ok"] and (
        args.skip_mesh or doc["mesh_parity"]["parity_ok"]
    )
    doc["fleet_parity_ok"] = ok
    text = json.dumps(doc, indent=2, sort_keys=True) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out} (fleet_parity_ok={ok})")
    else:
        sys.stdout.write(text)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
