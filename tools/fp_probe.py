"""Compare fp.mul implementations on the current backend: compile time and
steady-state latency of a 100-deep mul chain (the Miller loop's shape of
work). Usage: python tools/fp_probe.py {scan|fused|mxu} BATCH"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)
import jax.numpy as jnp
import numpy as np

mode = sys.argv[1]
batch = int(sys.argv[2])
if mode == "scan":
    os.environ["LODESTAR_TPU_LEGACY_FP"] = "1"
elif mode == "mxu":
    os.environ["LODESTAR_TPU_MXU_MUL"] = "1"
elif mode == "pallas":
    os.environ["LODESTAR_TPU_PALLAS_MUL"] = "1"
elif mode == "mxu2":
    os.environ["LODESTAR_TPU_PALLAS_MXU"] = "1"
elif mode == "padconv":
    os.environ["LODESTAR_TPU_PADCONV_FP"] = "1"

from lodestar_tpu.ops import fp  # noqa: E402

rng = np.random.default_rng(0)
a = jnp.asarray(rng.integers(0, 4096, (batch, 32), dtype=np.int32))
b = jnp.asarray(rng.integers(0, 4096, (batch, 32), dtype=np.int32))


def chain(a, b):
    for _ in range(100):
        a = fp.mul(a, b)
    return a


t0 = time.perf_counter()
f = jax.jit(chain)
r = f(a, b)
r.block_until_ready()
print(f"{mode} b={batch}: compile+first = {time.perf_counter()-t0:.1f}s", flush=True)
t0 = time.perf_counter()
for _ in range(5):
    r = f(a, b)
r.block_until_ready()
print(
    f"{mode} b={batch}: steady 100-mul chain = {(time.perf_counter()-t0)/5*1000:.1f} ms",
    flush=True,
)
