"""Benchmark regression gate over the committed BENCH history.

Reference analog: `.benchrc.yaml` — the reference CI runs every perf
test, compares against the committed benchmark history and FAILS the
run when a result degrades by more than `threshold` (3x). Here the
history is the driver's `BENCH_r*.json` round files (one per PR round,
`parsed` = the bench document) plus the latest run's
`bench_details.json`; VERDICT round 5 lists "continuous benchmark
regression tracking" as missing item #3 — this tool closes it.

    python tools/bench_compare.py                 # repo history, 3x gate
    python tools/bench_compare.py --threshold 1.5 # tighter gate
    python tools/bench_compare.py --dir /path     # synthetic histories (tests)

Comparison rules:
- rounds whose document never parsed (`parsed: null` — a timed-out run)
  carry no comparable rows and are skipped WITH a printed note, exactly
  like the reference skips benchmarks with no prior history; rounds the
  bench watchdog flushed partially (`timed_out: true`, round 7) parse
  but are likewise logged-and-skipped — a truncated run's rates are not
  a trend;
- rounds that ran DEGRADED (`supervisor.degraded: true` in the bench
  document: CPU-oracle fallbacks, an open circuit breaker, or an armed
  fault-injection plan — round 7) are skipped with a printed note: a
  round served by the CPU tier measures the wrong thing, and gating on
  it would either mask a device regression or flag a phantom one;
- rate-shaped keys (`*per_sec`) regress when they DROP by more than
  threshold; time-shaped keys (`*_s`, `*_ms`, `*_seconds`) regress when
  they GROW by more than threshold; other keys (counts, fractions,
  configs) are informational only;
- REQUIRED keys (`REQUIRED_GATED_KEYS`: the per-set floor and the
  no-flags e2e rate — round-6 acceptance rows) are matched by BASE name
  across phase-prefix renames, so moving a row between phases can't
  silently drop it out of the gate; a required key present in the prior
  round but MISSING from the current one fails the run (a disappeared
  row hides regressions as effectively as a slow one);
- every round's SLO section (the bench document's `slo` verdicts from
  observability/slo.py, round 16) is compared objective-by-objective:
  the report prints each objective's prev->curr state delta, and a
  CURRENT round with a `burning` objective fails the gate WITH THE
  OBJECTIVE'S NAME — an error budget burning is a regression even when
  every raw number sits inside the 3x band. Rounds predating the SLO
  engine report `n/a` and never gate. Degraded/timed-out rounds are
  still skipped from numeric comparison, but their burn state is
  REPORTED (the skip note carries which objectives were burning when
  the round died). `--slo-only` gates exclusively on SLO verdicts;
- fewer than two parseable rounds exits 0 with a note (nothing to gate
  against), never a false red;
- each round's cumulative XLA compile seconds (the bench document's
  `compile_ledger` section) is printed as an INFORMATIONAL prev->curr
  delta, never gated: compile time varies with cache warmth, and the
  warm/cold distinction lives in the ledger itself — but a silent 10x
  compile-cost growth should at least be visible in the report.

Exit code: 0 = no regression, 1 = at least one gated key regressed (or a
required key disappeared).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

DEFAULT_THRESHOLD = 3.0
# rows the gate must never lose track of, matched by base name (the part
# after any `phase.` prefix): the unconditional per-set floor and the
# default-configuration wire-to-verdict rate
REQUIRED_GATED_KEYS = (
    # emitted by the parity-gated `floor_fused_pairing` phase (named
    # `floor_batched_fe` in ISSUE 14, `worst_case` before); base-name
    # matching carries the trend across the phase renames, same kernel +
    # shape on both sides
    "device_sets_per_sec_floor_distinct_pk_and_msg",
    "e2e_wire_to_verdict_sets_per_sec",
    # the mesh-native serving rate (round-7 tentpole): the grouped kernel
    # through the production mesh dispatcher on this host's mesh
    "sharded_grouped_sets_per_sec",
    # zero-copy wire→verdict through the mesh raw twins (ISSUE 15):
    # the facade with a mesh attached, signature bytes decompressed
    # on-device per chip — the e2e acceptance row for mesh ingest
    "e2e_mesh_raw_sets_per_sec",
    # ISSUE 18: the fused full-pairing rate (emitted only where the
    # Pallas pairing knob resolves on — TPU deploys; absent history
    # skips the gate, so CPU-only rounds stay green)
    "device_sets_per_sec_fused_pairing",
    # ISSUE 18: the epoch-warm attestation-lane host-marshal rate (the
    # epoch table + H(msg) dedup win; parity-gated in its phase)
    "attestation_epoch_warm_sets_per_sec",
    # ISSUE 19: the cold-start SLO as a gated time row (direction: down —
    # a round whose serving-ready grew 3x regressed the restart story,
    # e.g. a broken AOT store silently degrading every boot to JIT)
    "serving_ready_seconds",
    # ISSUE 20: the two-level fleet serving rate (the grouped kernel
    # through the emulated 2-host (dcn, ici) mesh; absent history skips
    # the gate, so pre-fleet rounds stay green)
    "fleet_sets_per_sec",
)
REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)


def _numeric_rows(doc: dict) -> dict[str, float]:
    """Flatten one bench document into {key: value} comparable rows:
    the headline metric plus every numeric per-phase row (the
    bench_emit.BenchEmitter layout) or flat legacy-format key."""
    rows: dict[str, float] = {}
    if not isinstance(doc, dict):
        return rows
    metric = doc.get("metric")
    if metric and isinstance(doc.get("value"), (int, float)):
        rows[str(metric)] = float(doc["value"])
    phases = doc.get("phases")
    if isinstance(phases, dict):
        for phase, rec in phases.items():
            if not isinstance(rec, dict) or rec.get("status") not in (None, "ok"):
                continue  # timed-out/killed phases are not comparable
            for key, value in (rec.get("rows") or {}).items():
                if isinstance(value, (int, float)) and not isinstance(value, bool):
                    rows[f"{phase}.{key}"] = float(value)
    else:
        # legacy flat details document (rounds <= 5)
        for key, value in doc.items():
            if key in ("metric", "value", "vs_baseline", "partial"):
                continue
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                rows[str(key)] = float(value)
    return rows


def _compile_seconds(doc) -> float | None:
    """Cumulative XLA compile seconds from the document's compile-ledger
    section (observability/compile_ledger.py), or None when the round
    predates the ledger."""
    if not isinstance(doc, dict):
        return None
    section = doc.get("compile_ledger")
    if not isinstance(section, dict):
        return None
    value = section.get("cumulative_seconds")
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        return float(value)
    return None


def _slo_state(doc) -> dict | None:
    """{objective: state} + the burning list from the bench document's
    `slo` section (observability/slo.py), or None for rounds predating
    the SLO engine."""
    section = doc.get("slo") if isinstance(doc, dict) else None
    if not isinstance(section, dict):
        return None
    objectives = {
        o["name"]: o.get("state", "?")
        for o in section.get("objectives", ())
        if isinstance(o, dict) and o.get("name")
    }
    if not objectives:
        return None
    return {
        "objectives": objectives,
        "burning": sorted(k for k, v in objectives.items() if v == "burning"),
    }


def _print_burn_state(n: int, slo: dict | None) -> None:
    """One-line burn-state report for a round skipped from numeric
    comparison (degraded/timed-out): the skip must still say what the
    objectives looked like when the round died."""
    if slo is None:
        print(f"bench_compare: r{n:02d} burn state — n/a (round predates "
              "the SLO engine)")
    elif slo["burning"]:
        print(
            f"bench_compare: r{n:02d} burn state — BURNING: "
            f"{', '.join(slo['burning'])}"
        )
    else:
        print(
            f"bench_compare: r{n:02d} burn state — all "
            f"{len(slo['objectives'])} objectives ok"
        )


def _is_degraded(doc) -> bool:
    """A bench document that ran with CPU fallbacks / open breaker /
    armed faults labels itself via the emitter's `supervisor` section."""
    sup = doc.get("supervisor") if isinstance(doc, dict) else None
    return bool(isinstance(sup, dict) and sup.get("degraded"))


def load_history(root_dir: str, details_path: str | None = None) -> list[dict]:
    """[{n, rows}] for every round whose bench document parsed AND ran
    non-degraded, ascending by round number. `details_path`
    (bench_details.json) augments the LATEST round with its full
    per-phase row set (unless that document is itself degraded)."""
    rounds = []
    for path in glob.glob(os.path.join(root_dir, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            rec = json.load(open(path))
        except (OSError, ValueError) as e:
            print(
                f"bench_compare: skipping r{int(m.group(1)):02d} — "
                f"unreadable round file ({e})"
            )
            continue
        if not isinstance(rec, dict):
            # a `null` / truncated / list-shaped round file: carries no
            # rows, but must be a visible skip and never a traceback
            print(
                f"bench_compare: skipping r{int(m.group(1)):02d} — round "
                f"file is not a JSON object ({type(rec).__name__})"
            )
            continue
        parsed = rec.get("parsed") or {}
        if not isinstance(parsed, dict):
            print(
                f"bench_compare: skipping r{int(m.group(1)):02d} — "
                f"`parsed` is not a JSON object ({type(parsed).__name__})"
            )
            continue
        if not parsed:
            # `parsed: null` — the harness died before emitting; the
            # round carries no comparable rows but its absence from the
            # gate must be visible, not silent
            print(
                f"bench_compare: skipping r{int(m.group(1)):02d} — bench "
                "document never parsed (parsed: null; harness killed "
                "before emission)"
            )
            continue
        if _is_degraded(parsed):
            print(
                f"bench_compare: skipping r{int(m.group(1)):02d} — ran "
                "DEGRADED (CPU fallback / open breaker / faults armed); "
                "not comparable to device-path rounds"
            )
            _print_burn_state(int(m.group(1)), _slo_state(parsed))
            continue
        if parsed.get("timed_out"):
            # the watchdog/SIGTERM flushed a PARTIAL document before the
            # driver's kill: parseable, but its rates stop mid-run — log
            # and skip instead of gating a truncated round
            print(
                f"bench_compare: skipping r{int(m.group(1)):02d} — timed "
                "out mid-run (partial watchdog flush); rates not "
                "comparable to completed rounds"
            )
            _print_burn_state(int(m.group(1)), _slo_state(parsed))
            continue
        rows = _numeric_rows(parsed)
        if rows:
            rounds.append({
                "n": int(m.group(1)),
                "rows": rows,
                "compile_s": _compile_seconds(parsed),
                "slo": _slo_state(parsed),
            })
    rounds.sort(key=lambda r: r["n"])
    if rounds and details_path and os.path.exists(details_path):
        try:
            detail_doc = json.load(open(details_path))
            if _is_degraded(detail_doc) or (
                isinstance(detail_doc, dict) and detail_doc.get("timed_out")
            ):
                detail_rows = {}
            else:
                detail_rows = _numeric_rows(detail_doc)
        except (OSError, ValueError):
            detail_rows = {}
        # details belong to the newest run: augment without overriding
        # the round file's own headline
        for key, value in detail_rows.items():
            rounds[-1]["rows"].setdefault(key, value)
        if rounds[-1].get("compile_s") is None and detail_rows:
            rounds[-1]["compile_s"] = _compile_seconds(detail_doc)
        if rounds[-1].get("slo") is None and detail_rows:
            rounds[-1]["slo"] = _slo_state(detail_doc)
    return rounds


def compare_slo(prev: dict, curr: dict) -> tuple[list, list]:
    """(report_rows, regressions) for the SLO verdicts: every objective
    seen in either round gets a prev->curr state line, and an objective
    BURNING in the current round is a named regression — the whole point
    of the engine is that a burnt budget fails the gate by name."""
    prev_slo = prev.get("slo")
    curr_slo = curr.get("slo")
    report, regressions = [], []
    prev_obj = prev_slo["objectives"] if prev_slo else {}
    curr_obj = curr_slo["objectives"] if curr_slo else {}
    for name in sorted(set(prev_obj) | set(curr_obj)):
        p = prev_obj.get(name, "n/a")
        c = curr_obj.get(name, "n/a")
        burning_now = c == "burning"
        report.append((name, p, c, burning_now))
        if burning_now:
            regressions.append(f"slo:{name} (error budget burning)")
    return report, regressions


def _direction(key: str) -> str | None:
    """'up' = higher is better (rates), 'down' = lower is better
    (latencies), None = not gated."""
    base = key.rsplit(".", 1)[-1]
    if base.endswith("per_sec"):
        return "up"
    if base.endswith(("_s", "_ms", "_seconds")):
        return "down"
    if base == "fleet_overlap_fraction":
        # ISSUE 20: retained-throughput fraction of the two-level mesh
        # vs the flat mesh — a drop means the DCN collectives stopped
        # overlapping (e.g. a hierarchy regression re-crossing DCN per
        # bit-plane), which a raw rate row could hide behind faster chips
        return "up"
    return None


def _find_by_base(rows: dict, base: str):
    """(key, value) whose base name (after any `phase.` prefix) matches,
    or None. Exact-name match wins over a prefixed one."""
    if base in rows:
        return base, rows[base]
    for key, value in rows.items():
        if key.rsplit(".", 1)[-1] == base:
            return key, value
    return None


def compare(prev: dict, curr: dict, threshold: float) -> tuple[list, list]:
    """(report_rows, regressions) between two rounds' row dicts.

    Beyond the exact-key intersection, every REQUIRED_GATED_KEYS entry is
    resolved by base name on both sides so phase renames can't drop it;
    required keys present before but absent now count as regressions."""
    report, regressions = [], []
    compared = set()
    for key in sorted(set(prev["rows"]) & set(curr["rows"])):
        direction = _direction(key)
        if direction is None:
            continue
        p, c = prev["rows"][key], curr["rows"][key]
        if p <= 0 or c <= 0:
            continue  # zero/negative rows carry no trend information
        ratio = (p / c) if direction == "up" else (c / p)
        regressed = ratio > threshold
        report.append((key, direction, p, c, ratio, regressed))
        compared.add(key.rsplit(".", 1)[-1])
        if regressed:
            regressions.append(key)
    for base in REQUIRED_GATED_KEYS:
        if base in compared:
            continue
        prev_hit = _find_by_base(prev["rows"], base)
        curr_hit = _find_by_base(curr["rows"], base)
        if prev_hit is None:
            continue  # no history for this row yet — nothing to gate
        if curr_hit is None:
            # the row vanished: treat as a failed gate, not a silent skip
            report.append((base, "up", prev_hit[1], 0.0, float("inf"), True))
            regressions.append(f"{base} (missing from current round)")
            continue
        direction = _direction(base) or "up"
        p, c = prev_hit[1], curr_hit[1]
        if p <= 0 or c <= 0:
            continue
        ratio = (p / c) if direction == "up" else (c / p)
        regressed = ratio > threshold
        report.append((base, direction, p, c, ratio, regressed))
        if regressed:
            regressions.append(base)
    # ISSUE 20: fleet parity is a hard acceptance bit, not a trend — a
    # current round whose fleet_dryrun phase emitted fleet_parity_ok=0
    # diverged two-level verdicts from the flat mesh and fails outright,
    # whatever the rate rows say
    parity = _find_by_base(curr["rows"], "fleet_parity_ok")
    if parity is not None and parity[1] < 1:
        regressions.append(
            "fleet_parity_ok (two-level verdicts diverged from flat mesh)"
        )
    return report, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dir", default=REPO_ROOT,
                    help="directory holding BENCH_r*.json round files")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="regression factor that fails the gate (ref: 3x)")
    ap.add_argument("--details", default=None,
                    help="bench_details.json for the latest round "
                         "(default: <dir>/bench_details.json)")
    ap.add_argument("--slo-only", action="store_true",
                    help="gate exclusively on SLO verdicts (skip the "
                         "numeric threshold comparison)")
    args = ap.parse_args(argv)

    details = args.details or os.path.join(args.dir, "bench_details.json")
    history = load_history(args.dir, details_path=details)
    if len(history) < 2:
        if not history:
            print(
                f"bench_compare: no parseable bench history in {args.dir} "
                "— nothing to gate against (a fresh checkout or an "
                "all-degraded history is not a failure)"
            )
        else:
            print(
                f"bench_compare: 1 parseable round in {args.dir} — "
                "nothing to gate against"
            )
        return 0
    prev, curr = history[-2], history[-1]
    slo_report, slo_regressions = compare_slo(prev, curr)
    regressions = []
    if args.slo_only:
        print(
            f"bench_compare: r{prev['n']:02d} -> r{curr['n']:02d} "
            "(--slo-only: numeric thresholds skipped)"
        )
    else:
        report, regressions = compare(prev, curr, args.threshold)
        print(
            f"bench_compare: r{prev['n']:02d} -> r{curr['n']:02d} "
            f"({len(report)} gated keys, threshold {args.threshold}x)"
        )
        for key, direction, p, c, ratio, regressed in report:
            tag = "REGRESSION" if regressed else "ok"
            arrow = "^" if direction == "up" else "v"
            print(
                f"  {tag:>10}  {key} [{arrow}]  {p:.2f} -> {c:.2f}  "
                f"(worse x{ratio:.2f})" if ratio > 1.0 else
                f"  {tag:>10}  {key} [{arrow}]  {p:.2f} -> {c:.2f}  "
                f"(better x{1 / ratio:.2f})"
            )
        pc, cc = prev.get("compile_s"), curr.get("compile_s")
        if pc is not None or cc is not None:
            def _fmt(v):
                return f"{v:.1f}s" if v is not None else "n/a"

            print(
                f"  info        cumulative compile seconds {_fmt(pc)} -> "
                f"{_fmt(cc)} (informational; not gated — varies with cache "
                "warmth, see compile_ledger)"
            )
    if slo_report:
        print(f"  slo verdicts r{prev['n']:02d} -> r{curr['n']:02d}:")
        for name, p, c, burning_now in slo_report:
            tag = "BURNING" if burning_now else "ok"
            print(f"  {tag:>10}  slo:{name}  {p} -> {c}")
    else:
        print(
            "  info        no SLO verdicts in either round (rounds predate "
            "the SLO engine; not gated)"
        )
    failed = False
    if regressions:
        print(
            f"FAIL: {len(regressions)} key(s) regressed more than "
            f"{args.threshold}x: {', '.join(regressions)}"
        )
        failed = True
    if slo_regressions:
        print(
            f"FAIL: {len(slo_regressions)} SLO objective(s) burning their "
            f"error budget: {', '.join(slo_regressions)}"
        )
        failed = True
    if failed:
        return 1
    if args.slo_only:
        print("OK: no SLO objective is burning its error budget")
    else:
        print("OK: no gated key regressed past the threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
