"""Virtual-mesh scaling table for the sharded grouped verifier.

Runs the SAME total batch (64 root-rows × 64 lanes = 4096 sets) on
1/2/4/8-device virtual CPU meshes and records steady-state sets/s plus
verdict parity with the single-device kernel (VERDICT r2 next-step #7).
CPU-mesh numbers measure the SHARDING (collective layout, per-chip graph),
not TPU silicon — the table's point is that the ICI tier composes and
scales, with real-chip numbers to follow on multi-chip hardware.

Round-7 instrumentation: the 2-device row has sat anomalously BELOW the
4/8-device rows since round 4 (84 vs ~106 sets/s). To attribute it, each
sharded size is now timed twice — the full kernel AND a local-only probe
(`make_sharded_grouped_local_probe`: the per-chip body + u-plane
all_gather, root tail replaced by a psum checksum) — and per-rep times
are recorded so a one-off scheduler hiccup can't masquerade as a
structural cost. body_s vs full_s splits the anomaly into "data-parallel
body" vs "sequential tail + cross-chip product".
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from lodestar_tpu.utils.jax_env import force_platform

N_MAX = int(os.environ.get("MESH_MAX", "8"))
force_platform("cpu", N_MAX)

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)

import numpy as np
from jax.sharding import Mesh

REPS = int(os.environ.get("MESH_REPS", "3"))
# local-only probe sizes: the anomalous size + its healthy comparator
# (instrumenting 8 as well doubles nothing diagnostic and costs another
# deep compile on the 1-core box)
PROBE_SIZES = tuple(
    int(s) for s in os.environ.get("MESH_PROBE_SIZES", "2,4").split(",") if s
)


def _time_reps(fn) -> list[float]:
    times = []
    for _ in range(REPS):
        t0 = time.monotonic()
        out = fn()
        jax.block_until_ready(out)
        times.append(round(time.monotonic() - t0, 3))
    return times


def main():
    from __graft_entry__ import _example_grouped
    from lodestar_tpu.parallel.sharded import (
        ShardedGroupedVerifier,
        make_sharded_grouped_local_probe,
    )
    from lodestar_tpu.parallel.verifier import BatchVerifier

    rows, lanes = 64, 64
    g, a_bits, b_bits = _example_grouped(rows, lanes)
    table = []

    # single-device reference verdict (the unsharded kernel)
    bv = BatchVerifier(grouped_configs=((rows, lanes),))
    t0 = time.monotonic()
    ref = bool(bv.verify_grouped(g, a_bits, b_bits))
    compile_1 = time.monotonic() - t0
    times = _time_reps(lambda: bv.verify_grouped(g, a_bits, b_bits))
    dt = sum(times) / len(times)
    table.append(
        {"devices": 1, "sets_per_sec": round(rows * lanes / dt, 1),
         "verdict": ref, "compile_s": round(compile_1, 1),
         "rep_s": times}
    )
    print(table[-1], flush=True)
    assert ref, "reference verdict False on a valid batch"

    sizes = [n for n in (2, 4, 8) if n <= N_MAX]
    for n in sizes:
        mesh = Mesh(np.array(jax.devices()[:n]), axis_names=("dp",))
        v = ShardedGroupedVerifier(mesh)
        t0 = time.monotonic()
        ok = v.verify_grouped(g, a_bits, b_bits)
        compile_s = time.monotonic() - t0
        assert ok == ref, f"verdict parity broken at {n} devices"
        times = _time_reps(lambda: v.verify_grouped(g, a_bits, b_bits))
        dt = sum(times) / len(times)
        row = {"devices": n, "sets_per_sec": round(rows * lanes / dt, 1),
               "verdict": bool(ok), "compile_s": round(compile_s, 1),
               "rep_s": times,
               "per_chip_miller_lanes": 2 * (rows // n) + 64 // n}
        if n in PROBE_SIZES:
            probe = make_sharded_grouped_local_probe(mesh)
            sharding = v._sharding
            put = lambda x: jax.device_put(x, sharding)
            args = (put(g.pk_x), put(g.pk_y), put(g.msg_x), put(g.msg_y),
                    put(g.sig_x), put(g.sig_y), put(a_bits), put(b_bits),
                    put(g.valid))
            t0 = time.monotonic()
            jax.block_until_ready(probe(*args))
            row["body_compile_s"] = round(time.monotonic() - t0, 1)
            body_times = _time_reps(lambda: probe(*args))
            row["body_rep_s"] = body_times
            body_dt = sum(body_times) / len(body_times)
            row["body_s"] = round(body_dt, 3)
            row["tail_s"] = round(dt - body_dt, 3)
        table.append(row)
        print(table[-1], flush=True)

    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "MESH_SCALING.json"
    )
    note = (
        "All virtual devices share ONE physical core, so total throughput "
        "cannot rise with mesh size — this table measures SHARDING OVERHEAD "
        "(distance from the 1-device unsharded kernel), not silicon scaling. "
        "Round-4 fix still in force: the sequential tail runs on chip 0 only. "
        "Round-7 instrumentation: body_s times the data-parallel local body "
        "(+ u-plane all_gather) with the root tail replaced by a psum "
        "checksum; tail_s = full − body attributes the remainder to the "
        "cross-chip Fp12 product + final exp. rep_s lists raw per-rep "
        "times (reps=%d) so run-to-run noise is visible. See BASELINE.md "
        "§mesh for the 2-device-row analysis." % REPS
    )
    with open(out_path, "w") as f:
        json.dump({"shape": f"{rows}x{lanes}", "platform": "cpu-virtual",
                   "note": note, "reps": REPS, "table": table}, f, indent=2)
    print(json.dumps(table))


if __name__ == "__main__":
    main()
