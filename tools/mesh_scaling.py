"""Virtual-mesh scaling table for the sharded grouped verifier.

Runs the SAME total batch (64 root-rows × 64 lanes = 4096 sets) on
1/2/4/8-device virtual CPU meshes and records steady-state sets/s plus
verdict parity with the single-device kernel (VERDICT r2 next-step #7).
CPU-mesh numbers measure the SHARDING (collective layout, per-chip graph),
not TPU silicon — the table's point is that the ICI tier composes and
scales, with real-chip numbers to follow on multi-chip hardware.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from lodestar_tpu.utils.jax_env import force_platform

N_MAX = int(os.environ.get("MESH_MAX", "8"))
force_platform("cpu", N_MAX)

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".jax_cache"),
)

import numpy as np
from jax.sharding import Mesh


def main():
    from __graft_entry__ import _example_grouped
    from lodestar_tpu.parallel.sharded import ShardedGroupedVerifier
    from lodestar_tpu.parallel.verifier import BatchVerifier

    rows, lanes = 64, 64
    g, a_bits, b_bits = _example_grouped(rows, lanes)
    table = []

    # single-device reference verdict (the unsharded kernel)
    bv = BatchVerifier(grouped_configs=((rows, lanes),))
    t0 = time.monotonic()
    ref = bool(bv.verify_grouped(g, a_bits, b_bits))
    compile_1 = time.monotonic() - t0
    t0 = time.monotonic()
    reps = 2
    for _ in range(reps):
        out = bv.verify_grouped(g, a_bits, b_bits)
    jax.block_until_ready(out)
    dt = (time.monotonic() - t0) / reps
    table.append(
        {"devices": 1, "sets_per_sec": round(rows * lanes / dt, 1),
         "verdict": ref, "compile_s": round(compile_1, 1)}
    )
    print(table[-1], flush=True)
    assert ref, "reference verdict False on a valid batch"

    sizes = [n for n in (2, 4, 8) if n <= N_MAX]
    for n in sizes:
        mesh = Mesh(np.array(jax.devices()[:n]), axis_names=("dp",))
        v = ShardedGroupedVerifier(mesh)
        t0 = time.monotonic()
        ok = v.verify_grouped(g, a_bits, b_bits)
        compile_s = time.monotonic() - t0
        assert ok == ref, f"verdict parity broken at {n} devices"
        t0 = time.monotonic()
        for _ in range(reps):
            ok = v.verify_grouped(g, a_bits, b_bits)
        dt = (time.monotonic() - t0) / reps
        table.append(
            {"devices": n, "sets_per_sec": round(rows * lanes / dt, 1),
             "verdict": bool(ok), "compile_s": round(compile_s, 1)}
        )
        print(table[-1], flush=True)

    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "MESH_SCALING.json"
    )
    note = (
        "All virtual devices share ONE physical core, so total throughput "
        "cannot rise with mesh size — this table measures SHARDING OVERHEAD "
        "(distance from the 1-device unsharded kernel), not silicon scaling. "
        "Round-4 fix validated: the sequential Horner tail now runs on chip 0 "
        "only instead of replicated on every chip (parallel/sharded.py); "
        "round 3's 8-device collapse (66 sets/s, -45% vs unsharded) is gone "
        "- 8 shards now run within ~13% of the unsharded kernel, and "
        "PER-CHIP work decreases monotonically with mesh size."
    )
    with open(out_path, "w") as f:
        json.dump({"shape": f"{rows}x{lanes}", "platform": "cpu-virtual",
                   "note": note, "table": table}, f, indent=2)
    print(json.dumps(table))


if __name__ == "__main__":
    main()
