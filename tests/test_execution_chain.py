"""Post-merge chain e2e: a capella dev chain driven through the BeaconChain
with the mock EL — produce_block builds payloads via the engine, the import
pipeline verifies them, withdrawals sweep, sync-aggregate pool feeds blocks
(reference analog: merge-interop sim test, SURVEY.md §4.5)."""

import dataclasses

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain import BeaconChain, CpuBlsVerifier
from lodestar_tpu.config.beacon_config import (
    BeaconConfig,
    ChainForkConfig,
    compute_signing_root,
)
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.execution.engine import ExecutionEngineMock
from lodestar_tpu.params import (
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
    ETH1_ADDRESS_WITHDRAWAL_PREFIX,
    ForkName,
)
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.state_transition.altair import upgrade_state_to_altair
from lodestar_tpu.state_transition.bellatrix import upgrade_state_to_bellatrix
from lodestar_tpu.state_transition.block import _epoch_signing_root
from lodestar_tpu.state_transition.capella import upgrade_state_to_capella
from lodestar_tpu.types import get_types

N = 16
SPE = MINIMAL.SLOTS_PER_EPOCH
EL_GENESIS_HASH = b"\x01" * 32

ALL_FORKS_AT_GENESIS = dataclasses.replace(
    MINIMAL_CHAIN_CONFIG,
    ALTAIR_FORK_EPOCH=0,
    BELLATRIX_FORK_EPOCH=0,
    CAPELLA_FORK_EPOCH=0,
)


def _sk(i):
    return bls.interop_secret_key(i)


@pytest.fixture(scope="module")
def capella_chain():
    t = get_types(MINIMAL)
    fork_config = ChainForkConfig(ALL_FORKS_AT_GENESIS, MINIMAL)
    pre = interop_genesis_state(fork_config, t.phase0, N, genesis_time=1_600_000_000)
    config = BeaconConfig(
        ALL_FORKS_AT_GENESIS, bytes(pre.genesis_validators_root), MINIMAL
    )
    state = upgrade_state_to_altair(config, MINIMAL, pre, t.altair)
    state = upgrade_state_to_bellatrix(config, MINIMAL, state, t.bellatrix)
    state = upgrade_state_to_capella(config, MINIMAL, state, t.capella)
    # merge already complete at genesis: anchor the EL chain
    state.latest_execution_payload_header.block_hash = EL_GENESIS_HASH
    state.latest_execution_payload_header.timestamp = state.genesis_time
    # validator 0 withdraws continuously (excess balance, eth1 credential)
    state.validators[0].withdrawal_credentials = (
        ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\xaa" * 20
    )
    state.balances[0] = MINIMAL.MAX_EFFECTIVE_BALANCE + 1_000_000
    engine = ExecutionEngineMock(genesis_block_hash=EL_GENESIS_HASH)
    chain = BeaconChain(
        config,
        t.capella,
        state.copy(),
        verifier=CpuBlsVerifier(),
        execution_engine=engine,
    )
    return config, t.capella, chain, engine


def _sign_and_import(config, types, chain, block):
    domain = config.get_domain(DOMAIN_BEACON_PROPOSER, block.slot)
    sig = _sk(block.proposer_index).sign(
        compute_signing_root(block.hash_tree_root(), domain)
    )
    signed = types.SignedBeaconBlock(message=block, signature=sig.to_bytes())
    return chain.process_block(signed, verify_signatures=True)


def _sync_contributions(config, chain, types, slot, block_root):
    """Full-participation contributions for `block_root` into the pool."""
    from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_COUNT

    cached = chain.head_state
    domain = config.get_domain(DOMAIN_SYNC_COMMITTEE, slot, slot // SPE)
    root = compute_signing_root(block_root, domain)
    pk_to_idx = cached.epoch_ctx.pubkey_to_index
    pubkeys = list(cached.state.current_sync_committee.pubkeys)
    sub_size = MINIMAL.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
    for sub in range(SYNC_COMMITTEE_SUBNET_COUNT):
        sub_keys = pubkeys[sub * sub_size : (sub + 1) * sub_size]
        sigs = [_sk(pk_to_idx[bytes(pk)]).sign(root) for pk in sub_keys]
        chain.sync_contribution_pool.add(
            types.SyncCommitteeContribution(
                slot=slot,
                beacon_block_root=block_root,
                subcommittee_index=sub,
                aggregation_bits=[True] * sub_size,
                signature=bls.aggregate_signatures(sigs).to_bytes(),
            )
        )


def _randao_reveal(config, chain, slot):
    from lodestar_tpu.state_transition import process_slots

    pre = chain.head_state.copy()
    if slot > pre.state.slot:
        process_slots(pre, chain.types, slot)
    proposer = pre.epoch_ctx.get_beacon_proposer(slot)
    return (
        _sk(proposer)
        .sign(_epoch_signing_root(slot // SPE, config.get_domain(DOMAIN_RANDAO, slot)))
        .to_bytes()
    )


def test_capella_chain_produces_and_imports_payload_blocks(capella_chain):
    config, types, chain, engine = capella_chain
    start_balance_v0 = int(chain.head_state.flat.balances[0])
    for slot in range(1, SPE + 1):
        parent_root = chain.head_root
        _sync_contributions(config, chain, types, max(slot, 1) - 1, parent_root)
        randao = _randao_reveal(config, chain, slot)
        block = chain.produce_block(slot, randao)
        # a real (non-default) payload rides every block
        assert bytes(block.body.execution_payload.block_hash) != b"\x00" * 32
        _sign_and_import(config, types, chain, block)
    head = chain.head_state
    assert head.state.slot == SPE
    assert head.fork == ForkName.capella
    # EL followed the beacon head
    assert engine.head == bytes(
        head.state.latest_execution_payload_header.block_hash
    )
    # withdrawals swept validator 0's excess down
    assert int(head.flat.balances[0]) <= start_balance_v0
    assert head.state.next_withdrawal_index > 0
    # sync aggregates were included with full participation
    head_block = chain.blocks[chain.head_root]
    assert all(head_block.message.body.sync_aggregate.sync_committee_bits)


def test_invalid_payload_rejected(capella_chain):
    config, types, chain, engine = capella_chain
    slot = chain.head_state.state.slot + 1
    randao = _randao_reveal(config, chain, slot)
    block = chain.produce_block(slot, randao)
    engine.invalid_hashes.add(bytes(block.body.execution_payload.block_hash))
    with pytest.raises(Exception, match="payload"):
        _sign_and_import(config, types, chain, block)
    engine.invalid_hashes.clear()


def test_prepare_next_slot_scheduler(capella_chain):
    config, types, chain, engine = capella_chain
    slot = chain.head_state.state.slot
    chain.prepare_next_slot.on_slot(slot)
    prepared = chain.prepare_next_slot.get_prepared(slot + 1)
    assert prepared is not None
    assert prepared.state.slot == slot + 1
    # the engine has a building session kicked off for the next slot
    assert engine._building
