"""BlsLaneDispatcher policy tests (ISSUE 15 tentpole C): priority
lanes, admission control / load-shedding, eviction order, continuous-
batching overlap, and shutdown semantics.

Everything here drives the HOST-side dispatcher state machine with mock
verifiers (no crypto, no jax) so it stays in the fast tier. The key
regression (satellite 6): a shed waiter must get its typed
`BlsShedError` PROMPTLY — never ride out the 300 s waiter timeout.
"""

import threading
import time

import pytest

from lodestar_tpu.chain.bls_verifier import BlsShedError, MockBlsVerifier
from lodestar_tpu.chain.dispatcher import BlsLaneDispatcher, DEFAULT_LANE, LANES
from lodestar_tpu.observability.stages import PipelineMetrics

# Far above any prompt-shed assertion: if a test waits anywhere near
# this, the dispatcher hung a waiter instead of rejecting it.
WAITER_TIMEOUT_S = 60.0


class _GateVerifier(MockBlsVerifier):
    """Mock whose verify blocks until `gate` is set — holds workers
    in-flight so queues accumulate deterministically."""

    def __init__(self):
        super().__init__(result=True)
        self.gate = threading.Event()
        self.started = threading.Event()
        self._lock = threading.Lock()
        self.calls: list[list] = []

    def verify_signature_sets(self, sets) -> bool:
        with self._lock:
            self.calls.append(list(sets))
        self.started.set()
        self.gate.wait(10.0)
        return super().verify_signature_sets(sets)


def _dispatcher(verifier=None, **kw):
    kw.setdefault("max_sigs", 32)
    kw.setdefault("max_wait_ms", 10_000)  # timer never fires in-test
    kw.setdefault("workers", 1)
    kw.setdefault("pending_cap", 0)  # off unless a test opts in
    kw.setdefault("lane_caps", {})
    kw.setdefault("waiter_timeout_s", WAITER_TIMEOUT_S)
    kw.setdefault("pipeline", PipelineMetrics())
    return BlsLaneDispatcher(verifier or MockBlsVerifier(), **kw)


def _submit_bg(d, sets, lane):
    """Submit from a background thread; returns (thread, outcome list)."""
    out: list = []

    def run():
        try:
            out.append(("ok", d.verify_signature_sets(sets, lane=lane)))
        except BlsShedError as e:
            out.append(("shed", e))

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, out


def _wait_queued(d, n_sets, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if d._lanes_state()["pending_sets"] >= n_sets:
            return
        time.sleep(0.005)
    raise AssertionError(f"never saw {n_sets} queued sets")


def test_shed_waiter_gets_prompt_typed_rejection():
    """Satellite-6 regression: admission shed raises BlsShedError in the
    CALLER within milliseconds, not after the waiter timeout."""
    d = _dispatcher(lane_caps={"attestation": 2})
    try:
        t1, o1 = _submit_bg(d, ["a1", "a2"], "attestation")
        _wait_queued(d, 2)
        t0 = time.monotonic()
        with pytest.raises(BlsShedError) as ei:
            d.verify_signature_sets(["a3"], lane="attestation")
        elapsed = time.monotonic() - t0
        assert elapsed < 1.0, f"shed took {elapsed:.1f}s — waiter-timeout ride"
        assert ei.value.lane == "attestation"
        assert ei.value.n_sets == 1
        assert "shed" in str(ei.value)
    finally:
        d.close()
        t1.join(timeout=5.0)
    # the queued waiter was resolved promptly by close(), typed the same
    assert o1 and o1[0][0] == "shed"


def test_block_lane_is_never_shed_and_evicts_attestations():
    """A block arriving into a full queue evicts queued attestations
    (prompt typed rejection for them) and is itself admitted + verified."""
    inner = MockBlsVerifier()
    d = _dispatcher(inner, pending_cap=2)
    try:
        t1, o1 = _submit_bg(d, ["a1", "a2"], "attestation")
        _wait_queued(d, 2)
        t0 = time.monotonic()
        assert d.verify_signature_sets(["b1", "b2"], lane="block") is True
        assert time.monotonic() - t0 < 5.0
        t1.join(timeout=5.0)
        assert o1 and o1[0][0] == "shed"
        assert "evicted" in str(o1[0][1])
    finally:
        d.close()


def test_eviction_stops_at_equal_or_higher_priority_lanes():
    """Overflow frees the LOWEST-priority queued sets first and leaves
    higher lanes' queues untouched once enough is freed."""
    d = _dispatcher(pending_cap=4)
    try:
        ta, oa = _submit_bg(d, ["att1", "att2"], "attestation")
        _wait_queued(d, 2)
        tg, og = _submit_bg(d, ["agg1", "agg2"], "aggregate")
        _wait_queued(d, 4)
        # +2 sync_committee sets overflow by 2 → exactly the attestation
        # entry is evicted; the aggregate entry must survive
        ts, os_ = _submit_bg(d, ["sc1", "sc2"], "sync_committee")
        ta.join(timeout=5.0)
        assert oa and oa[0][0] == "shed"
        state = d._lanes_state()
        assert state["lanes"]["attestation"]["queued_sets"] == 0
        assert state["lanes"]["aggregate"]["queued_sets"] == 2
        assert state["lanes"]["sync_committee"]["queued_sets"] == 2
    finally:
        d.close()
        for t in (tg, ts):
            t.join(timeout=5.0)
    assert og and og[0][0] == "shed"  # resolved by close, not hung
    assert os_ and os_[0][0] == "shed"


def test_batch_drains_in_strict_lane_priority_order():
    """Entries coalesce into one device batch in lane order — a block's
    sets ride ahead of an earlier-queued attestation."""
    inner = _GateVerifier()
    d = _dispatcher(inner, max_wait_ms=10, max_sigs=64)
    try:
        tp, op = _submit_bg(d, ["primer"], "aggregate")
        assert inner.started.wait(5.0)  # worker now in-flight, gated
        ta, oa = _submit_bg(d, ["att"], "attestation")
        _wait_queued(d, 1)
        tb, ob = _submit_bg(d, ["blk"], "block")
        _wait_queued(d, 2)
        inner.gate.set()
        for t in (tp, ta, tb):
            t.join(timeout=10.0)
        assert op == [("ok", True)] and oa == [("ok", True)] and ob == [("ok", True)]
        # second merged batch: block sets first despite arriving last
        assert inner.calls[0] == ["primer"]
        assert inner.calls[1] == ["blk", "att"]
    finally:
        d.close()


def test_overlap_dispatch_while_device_busy():
    """With 2 workers, a half-batch dispatches WHILE another batch is
    in flight (reason=overlap) and the overlap gauge records it."""
    inner = _GateVerifier()
    pipeline = PipelineMetrics()
    d = _dispatcher(inner, workers=2, max_wait_ms=10, max_sigs=4,
                    pipeline=pipeline)
    try:
        tp, op = _submit_bg(d, ["primer"], "aggregate")
        assert inner.started.wait(5.0)
        # 2 sets ≥ max_sigs//2 → second worker picks them up immediately
        ta, oa = _submit_bg(d, ["x1", "x2"], "attestation")
        deadline = time.monotonic() + 5.0
        while len(inner.calls) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(inner.calls) == 2, "overlap batch never dispatched"
        inner.gate.set()
        for t in (tp, ta):
            t.join(timeout=10.0)
        assert op == [("ok", True)] and oa == [("ok", True)]
        snap = pipeline.lanes_snapshot()
        assert snap["batches"] == 2
        assert snap["overlapped_batches"] == 1
        assert snap["overlap_fraction"] == 0.5
    finally:
        d.close()


def test_breaker_open_halves_effective_lane_caps():
    inner = MockBlsVerifier()
    inner.breaker_state = "open"  # supervised-verifier duck type
    d = _dispatcher(inner, lane_caps={"attestation": 4})
    try:
        t1, o1 = _submit_bg(d, ["a1", "a2"], "attestation")
        _wait_queued(d, 2)
        # cap 4 halves to 2 while the breaker is open: 2+1 > 2 → shed
        with pytest.raises(BlsShedError):
            d.verify_signature_sets(["a3"], lane="attestation")
        # breaker closed again: the full cap applies and the same
        # request admits (queued alongside the first entry)
        inner.breaker_state = "closed"
        t2, o2 = _submit_bg(d, ["a3"], "attestation")
        _wait_queued(d, 3)
    finally:
        d.close()
        t1.join(timeout=5.0)
        t2.join(timeout=5.0)
    assert o1 and o1[0][0] == "shed"
    assert o2 and o2[0][0] == "shed"  # resolved promptly by close()


def test_unknown_lane_routes_to_default_and_nonbatchable_bypasses():
    inner = MockBlsVerifier()
    d = _dispatcher(inner, lane_caps={DEFAULT_LANE: 1})
    try:
        # unknown lane falls back to the default lane, whose cap of 1
        # sheds this 2-set request at admission — proving the routing
        with pytest.raises(BlsShedError) as ei:
            d.verify_signature_sets(["s1", "s2"], lane="bogus_topic")
        assert ei.value.lane == DEFAULT_LANE
        # batchable=False bypasses the queue entirely (direct call)
        assert d.verify_signature_sets(["s1"], batchable=False) is True
        assert inner.sets_seen == 1
    finally:
        d.close()


def test_close_sheds_queued_waiters_and_goes_direct():
    inner = MockBlsVerifier()
    d = _dispatcher(inner)
    t1, o1 = _submit_bg(d, ["a1"], "attestation")
    _wait_queued(d, 1)
    t0 = time.monotonic()
    d.close()
    t1.join(timeout=5.0)
    assert time.monotonic() - t0 < 5.0
    assert o1 and o1[0][0] == "shed"
    assert "closed" in str(o1[0][1])
    d.close()  # idempotent
    # post-close verifies still work, routed straight to the verifier
    assert d.verify_signature_sets(["s1"], lane="attestation") is True
    assert inner.sets_seen == 1
    state = d._lanes_state()
    assert state["closed"] is True and state["pending_sets"] == 0


def test_lanes_snapshot_wiring():
    pipeline = PipelineMetrics()
    assert pipeline.lanes_snapshot() is None  # nothing bound yet
    d = _dispatcher(pipeline=pipeline, pending_cap=64,
                    lane_caps={"attestation": 8})
    try:
        snap = pipeline.lanes_snapshot()
        assert set(snap["lanes"]) == set(LANES)
        assert snap["lanes"]["attestation"]["cap"] == 8
        assert snap["pending_cap"] == 64
        assert snap["workers"] == 1
        assert snap["closed"] is False
        assert snap["sheds"] == {}
    finally:
        d.close()


def test_validation_lane_hint_capability_detection():
    """`_verify_lane` passes the lane only to facades that accept it —
    detected from the signature (incl. **kwargs), never by TypeError."""
    from lodestar_tpu.chain.validation import _verify_lane

    class _LaneAware:
        def __init__(self):
            self.lanes = []

        def verify_signature_sets(self, sets, batchable=True, lane="x"):
            self.lanes.append(lane)
            return True

    class _Kwargs:
        def __init__(self):
            self.kw = []

        def verify_signature_sets(self, sets, **kwargs):
            self.kw.append(kwargs)
            return True

    class _Legacy:
        def verify_signature_sets(self, sets):
            if len(sets) == 0:
                raise TypeError("must not be swallowed")
            return True

    aware = _LaneAware()
    assert _verify_lane(aware, ["s"], "attestation") is True
    assert aware.lanes == ["attestation"]

    kw = _Kwargs()
    assert _verify_lane(kw, ["s"], "sync_committee") is True
    assert kw.kw == [{"lane": "sync_committee"}]

    assert _verify_lane(_Legacy(), ["s"], "attestation") is True
    with pytest.raises(TypeError):
        # a TypeError raised INSIDE verification propagates untouched
        _verify_lane(_Legacy(), [], "attestation")


def test_overlap_fraction_gauge_exported_before_first_flood():
    """ISSUE 16 satellite: `lodestar_bls_lane_overlap_fraction` must be a
    live series from dispatcher construction — before the first flood,
    /debug/lanes and /metrics showed no overlap series at all, so a
    dashboard couldn't tell "no overlap yet" from "not wired"."""
    p = PipelineMetrics()
    d = _dispatcher(pipeline=p)
    try:
        assert p.lane_overlap_fraction.value() == 0.0
        assert "lodestar_bls_lane_overlap_fraction 0" in p.registry.expose()
    finally:
        d.close()
