"""Secure transport + live req/resp: handshake, muxing, typed requests.

Covers the libp2p-bundle equivalent (reference `network/nodejs/bundle.ts`:
TCP + noise + mplex) and reqresp-over-streams (`network/reqresp/reqResp.ts`)
with two real nodes over real TCP sockets.
"""

import asyncio

import pytest

# secure transport (secp256k1 identities, noise) needs the
# `cryptography` wheel, which minimal CI images may lack — skip, not error
pytest.importorskip("cryptography")

from lodestar_tpu.network.transport import (
    NodeIdentity,
    Transport,
    peer_id_from_pubkey,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30.0))


async def _pair():
    a, b = Transport(NodeIdentity.from_seed(b"a")), Transport(NodeIdentity.from_seed(b"b"))
    host, port = await b.listen()
    conn_ab = await a.dial(host, port)
    # wait for b to register the inbound connection
    for _ in range(100):
        if a.peer_id in b.connections:
            break
        await asyncio.sleep(0.01)
    return a, b, conn_ab


def test_handshake_authenticates_both_peers():
    async def main():
        a, b, conn_ab = await _pair()
        assert conn_ab.peer_id == b.peer_id
        assert b.connections[a.peer_id].peer_id == a.peer_id
        assert peer_id_from_pubkey(conn_ab.remote_pubkey) == b.peer_id
        await a.close()
        await b.close()

    run(main())


def test_stream_echo_roundtrip():
    async def main():
        a, b, conn_ab = await _pair()

        async def echo(stream):
            data = await stream.read_all(timeout=5)
            await stream.write(data[::-1])
            await stream.close()

        b.set_stream_handler("/test/echo/1", echo)
        stream = await conn_ab.open_stream("/test/echo/1")
        await stream.write(b"hello mux")
        await stream.close()
        assert await stream.read_all(timeout=5) == b"xum olleh"
        await a.close()
        await b.close()

    run(main())


def test_concurrent_streams_are_independent():
    async def main():
        a, b, conn_ab = await _pair()

        async def double(stream):
            data = await stream.read_all(timeout=5)
            await stream.write(data * 2)
            await stream.close()

        b.set_stream_handler("/test/double/1", double)

        async def one(payload: bytes) -> bytes:
            s = await conn_ab.open_stream("/test/double/1")
            await s.write(payload)
            await s.close()
            return await s.read_all(timeout=5)

        results = await asyncio.gather(*(one(bytes([i]) * (i + 1)) for i in range(10)))
        for i, res in enumerate(results):
            assert res == bytes([i]) * (i + 1) * 2
        await a.close()
        await b.close()

    run(main())


def test_unknown_protocol_resets_stream():
    async def main():
        a, b, conn_ab = await _pair()
        from lodestar_tpu.network.transport import StreamReset

        stream = await conn_ab.open_stream("/no/such/protocol")
        with pytest.raises((StreamReset, TimeoutError)):
            await stream.write(b"x")  # may already be reset
            for _ in range(50):
                if await stream.read(timeout=1.0) is None:
                    raise TimeoutError("closed without reset")
        await a.close()
        await b.close()

    run(main())


def test_large_payload_chunked_over_frames():
    async def main():
        a, b, conn_ab = await _pair()
        payload = bytes(range(256)) * (20_000)  # ~5 MB > MAX_FRAME

        async def sink(stream):
            data = await stream.read_all(timeout=15)
            await stream.write(len(data).to_bytes(8, "little"))
            await stream.close()

        b.set_stream_handler("/test/sink/1", sink)
        s = await conn_ab.open_stream("/test/sink/1")
        await s.write(payload)
        await s.close()
        out = await s.read_all(timeout=15)
        assert int.from_bytes(out, "little") == len(payload)
        await a.close()
        await b.close()

    run(main())


def test_mitm_without_identity_key_fails_handshake():
    """A dialer that reaches a different node than intended still gets an
    authenticated peer id — impersonation requires the private key."""

    async def main():
        real = Transport(NodeIdentity.from_seed(b"real"))
        imposter = Transport(NodeIdentity.from_seed(b"imposter"))
        host, port = await imposter.listen()
        dialer = Transport(NodeIdentity.from_seed(b"dialer"))
        conn = await dialer.dial(host, port)
        assert conn.peer_id == imposter.peer_id
        assert conn.peer_id != real.peer_id
        await dialer.close()
        await imposter.close()

    run(main())


def test_garbage_handshake_rejected():
    async def main():
        b = Transport(NodeIdentity.from_seed(b"b"))
        host, port = await b.listen()
        reader, writer = await asyncio.open_connection(host, port)
        writer.write(b"\x00\x00\x00\x20" + b"\xff" * 32)  # bogus ephemeral
        await writer.drain()
        # server must reject (connection closes without a valid msg2 auth)
        try:
            data = await asyncio.wait_for(reader.read(4096), 5.0)
        except (asyncio.TimeoutError, ConnectionError):
            data = b""
        # whatever came back, no connection is adopted
        await asyncio.sleep(0.1)
        assert len(b.connections) == 0
        writer.close()
        await b.close()

    run(main())
