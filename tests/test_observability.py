"""Pipeline telemetry (ISSUE 1): stage timers, planner counters, queue
gauges, and the structured bench emitter.

Kernel dispatches are STUBBED at the `BatchVerifier` seam so the full
host path (marshal, planner, caches, buffering, metrics) runs in the
fast suite without paying XLA compiles; the real-kernel twin lives in
tests/test_buffered_verifier.py (slow)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu import native
from lodestar_tpu.metrics import create_beacon_metrics
from lodestar_tpu.observability.bench_emit import BenchEmitter
from lodestar_tpu.observability.stages import PipelineMetrics

needs_native = pytest.mark.skipif(
    not native.HAVE_NATIVE_BLS, reason="native BLS tier unavailable"
)


def _sets(n, shared_root=True, salt=0):
    """n sets from n distinct keys; one shared signing root (the
    committee-gossip shape the root-grouped planner routes) or n
    distinct roots."""
    out = []
    for i in range(n):
        sk = bls.interop_secret_key(i + salt)
        msg = (
            b"\x42" * 32
            if shared_root
            else bytes([i & 0xFF, salt & 0xFF]) + b"\x17" * 30
        )
        out.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return out


def _stub_kernels(verifier, verdict=True):
    """Replace every device dispatch with a constant verdict (shapes and
    marshalling still run for real)."""
    k = verifier.kernels
    ret = lambda *a, **kw: np.bool_(verdict)
    k.verify_batch = ret
    k.verify_batch_raw = ret
    k.verify_grouped = ret
    k.verify_grouped_raw = ret
    k.verify_pk_grouped = ret
    k.verify_pk_grouped_raw = ret
    k.verify_individual = lambda arrs, *a, **kw: np.full(
        arrs.valid.shape, verdict
    )
    # bisection-verdict seam: an all-`verdict` tree whose root reports
    # `verdict` and whose levels let the host bisect when False
    def bisect_tree(arrs, r_bits):
        m = 1 << max(0, (arrs.valid.shape[0] - 1).bit_length())
        levels = []
        n = m
        while n >= 1:
            levels.append(np.zeros((n, 2, 3, 2, 32), np.int32))
            if n == 1:
                break
            n //= 2
        return np.bool_(verdict), levels

    k.verify_bisect_tree = bisect_tree
    k.probe_nodes = lambda fs: np.full((fs.shape[0],), verdict)


# --- stage timers / planner counters -----------------------------------------


def test_stage_timer_records_and_exposes():
    p = PipelineMetrics()
    with p.stage("marshal"):
        time.sleep(0.002)
    with p.stage("dispatch") as s:
        s.bound(np.zeros(3))  # block_until_ready no-ops on host arrays
    snap = p.stage_snapshot()
    assert snap["marshal"]["count"] == 1 and snap["marshal"]["sum_s"] > 0
    assert snap["dispatch"]["count"] == 1
    text = p.registry.expose()
    assert 'lodestar_bls_pipeline_stage_seconds_bucket' in text
    assert 'stage="marshal"' in text


@needs_native
def test_planner_counters_root_grouped_path():
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    p = PipelineMetrics()
    v = TpuBlsVerifier(observer=p)
    _stub_kernels(v)
    sets = _sets(8)  # one shared root, 8 signers -> root-grouped plan
    assert v.verify_signature_sets(sets)
    assert p.planner_decisions.value(path="root_grouped") == 1
    assert p.planner_sets.value(path="root_grouped") == 8
    # one group row of 8 sets observed
    assert p.planner_group_size._totals[()] == 1
    snap = p.stage_snapshot()
    assert snap["marshal"]["count"] >= 1
    assert snap["dispatch"]["count"] >= 1
    assert snap["device_wait"]["count"] >= 1
    # dedup caches saw the pubkeys and the shared root
    assert p.cache_events.value(cache="pk", outcome="miss") == 8
    assert p.cache_events.value(cache="h2c", outcome="miss") >= 1


@needs_native
def test_planner_counters_per_set_and_individual_paths():
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    p = PipelineMetrics()
    v = TpuBlsVerifier(observer=p)
    _stub_kernels(v)
    sets = _sets(3, shared_root=False)  # distinct roots AND keys: nothing groups
    assert v.verify_signature_sets(sets)
    assert p.planner_decisions.value(path="per_set") == 1
    out = v.verify_signature_sets_individual(sets)
    assert out == [True, True, True]
    assert p.planner_decisions.value(path="individual") == 1
    # the all-valid bisection fast path: one clean batch, zero rounds
    snap = p.bisect_snapshot()
    assert snap["batches"] == {"clean": 1}
    assert snap["rounds"] == 0 and snap["probes"] == 0


@needs_native
def test_bisect_counters_on_failed_root(monkeypatch):
    """A failed tree root walks the host bisection driver: rounds and
    probes tick, failed leaves surface as False (kernels stubbed — the
    probe reports every node failed, so every set comes back invalid)."""
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    p = PipelineMetrics()
    v = TpuBlsVerifier(observer=p)
    _stub_kernels(v, verdict=False)
    sets = _sets(4, shared_root=False)
    out = v.verify_signature_sets_individual(sets)
    assert out == [False] * 4
    snap = p.bisect_snapshot()
    assert snap["batches"] == {"bisected": 1}
    assert snap["rounds"] == 2  # log2(4) levels below the root
    assert snap["probes"] > 0
    assert p.stage_seconds._totals.get(("bisect",), 0) >= 1


@needs_native
def test_decompress_fallback_logged_and_counted():
    """A device-decompress batch the native tier can't marshal (65-byte
    message) must tick the fallback counter — the default-path downgrade
    is visible, not silent (round-6 satellite)."""
    from lodestar_tpu.chain.bls_verifier import DeviceBlsVerifier

    m = create_beacon_metrics()
    dev = DeviceBlsVerifier(observer=m.pipeline)
    _stub_kernels(dev._inner)
    assert dev._inner._device_decompress  # default-on since round 6
    sk = bls.interop_secret_key(1)
    odd_msg = b"\x55" * 65  # not a 32-byte root: native tier ineligible
    sets = [
        bls.SignatureSet(
            pubkey=sk.to_public_key(),
            message=odd_msg,
            signature=sk.sign(odd_msg).to_bytes(),
        )
    ]
    assert dev.verify_signature_sets(sets)
    assert m.pipeline.decompress_fallbacks.value() == 1
    # native-eligible batches do NOT tick the counter
    assert dev.verify_signature_sets(_sets(3))
    assert m.pipeline.decompress_fallbacks.value() == 1
    text = m.registry.expose()
    assert "lodestar_bls_verifier_decompress_fallback_total 1" in text


# --- the acceptance path: ThreadBufferedVerifier -> /metrics -----------------


@needs_native
def test_thread_buffered_device_verifier_updates_metrics_exposition():
    """verify_signature_sets through ThreadBufferedVerifier over the
    device tier updates a stage histogram, the planner-path counter and
    the queue-depth gauge, all visible on /metrics (ISSUE 1 acceptance;
    dispatches stubbed — the real-kernel twin is in the slow suite)."""
    from lodestar_tpu.chain.bls_verifier import (
        DeviceBlsVerifier,
        ThreadBufferedVerifier,
    )

    m = create_beacon_metrics()
    dev = DeviceBlsVerifier(observer=m.pipeline)
    _stub_kernels(dev._inner)
    tbv = ThreadBufferedVerifier(dev, max_sigs=6, max_wait_ms=5000, prom=m)

    # size-triggered flush: two sub-threshold requests cross max_sigs
    # together; the second caller flushes inline and resolves both
    first = []
    ta = threading.Thread(
        target=lambda: first.append(
            tbv.verify_signature_sets(_sets(3), batchable=True)
        )
    )
    ta.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and m.pipeline.buffer_depth.value() != 3:
        time.sleep(0.005)
    assert m.pipeline.buffer_depth.value() == 3  # queue gauge went up
    assert tbv.verify_signature_sets(_sets(3, salt=20), batchable=True)
    ta.join(timeout=10.0)
    assert first == [True]
    assert m.pipeline.flushes.value(reason="size") == 1

    # timer-triggered flush with a visible queue-depth transition
    tbv.max_wait = 0.15
    holder = []
    t = threading.Thread(
        target=lambda: holder.append(
            tbv.verify_signature_sets(_sets(2, salt=40), batchable=True)
        )
    )
    t.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if m.pipeline.buffer_depth.value() == 2:
            break
        time.sleep(0.005)
    assert m.pipeline.buffer_depth.value() == 2  # live callback gauge
    t.join(timeout=10.0)
    assert holder == [True]
    assert m.pipeline.buffer_depth.value() == 0
    assert m.pipeline.flushes.value(reason="timer") == 1
    assert m.pipeline.flush_seconds._totals[()] == 2

    text = m.registry.expose()
    assert "lodestar_bls_pipeline_stage_seconds_bucket" in text
    assert 'stage="marshal"' in text
    assert (
        'lodestar_bls_verifier_planner_decisions_total{path="root_grouped"}'
        in text
    )
    assert "lodestar_bls_verifier_buffer_depth 0" in text
    assert 'lodestar_bls_verifier_flushes_total{reason="size"} 1' in text
    assert 'lodestar_bls_verifier_flushes_total{reason="timer"} 1' in text


def test_metrics_server_profiler_endpoints():
    """/profiler/start|stop round-trip against stub hooks (the jax-real
    path shares the same `observability.trace` switch)."""
    import urllib.request

    from lodestar_tpu.metrics import MetricsRegistry, MetricsServer

    state = {"dir": None}

    def start(d=None):
        if state["dir"] is not None:
            return None
        state["dir"] = d or "/tmp/t"
        return state["dir"]

    def stop():
        d, state["dir"] = state["dir"], None
        return d

    server = MetricsServer(
        MetricsRegistry(), port=0, profiler_start=start, profiler_stop=stop
    )
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{url}/profiler/start?dir=/tmp/x") as r:
            assert json.load(r) == {"status": "started", "dir": "/tmp/x"}
        # double start -> 409
        try:
            urllib.request.urlopen(f"{url}/profiler/start")
            assert False, "expected 409"
        except urllib.error.HTTPError as e:
            assert e.code == 409
        with urllib.request.urlopen(f"{url}/profiler/stop") as r:
            assert json.load(r)["status"] == "stopped"
        with urllib.request.urlopen(f"{url}/metrics") as r:
            assert r.status == 200
    finally:
        server.close()


def test_metrics_server_debug_mesh_endpoint():
    """/debug/mesh serves the dispatcher snapshot when wired (round 7),
    and reports wired:false when the node serves unmeshed."""
    import urllib.request

    from lodestar_tpu.metrics import MetricsRegistry, MetricsServer

    snap = {"size": 2, "healthy": [0, 1], "evicted": []}
    server = MetricsServer(MetricsRegistry(), port=0, mesh=lambda: snap)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/mesh"
        with urllib.request.urlopen(url) as r:
            assert json.load(r) == {"wired": True, **snap}
    finally:
        server.close()

    server = MetricsServer(MetricsRegistry(), port=0, mesh=lambda: None)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/mesh"
        with urllib.request.urlopen(url) as r:
            assert json.load(r) == {"wired": False}
    finally:
        server.close()


def test_metrics_server_debug_fleet_endpoint():
    """/debug/fleet serves the two-level fleet census when wired
    (ISSUE 20), and reports wired:false on single-host/unmeshed nodes."""
    import urllib.request

    from lodestar_tpu.metrics import MetricsRegistry, MetricsServer

    snap = {
        "hosts_total": 2,
        "hosts_serving": 2,
        "layout": {"0": [0, 1], "1": [2, 3]},
        "host_dispatches": {"0": 2, "1": 2},
        "evicted_hosts": [],
        "router": {"hosts": 2, "rank": 0, "owned": 29},
    }
    server = MetricsServer(MetricsRegistry(), port=0, fleet=lambda: snap)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/fleet"
        with urllib.request.urlopen(url) as r:
            assert json.load(r) == {"wired": True, **snap}
    finally:
        server.close()

    # single-host dispatchers return None from fleet_snapshot()
    server = MetricsServer(MetricsRegistry(), port=0, fleet=lambda: None)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/fleet"
        with urllib.request.urlopen(url) as r:
            assert json.load(r) == {"wired": False}
    finally:
        server.close()


def test_metrics_server_debug_epoch_table_endpoint():
    """/debug/epoch_table serves the table snapshot when wired (ISSUE 18),
    reports wired:false when the table is disabled or absent, and maps a
    snapshot-callable failure to a 500 instead of killing the server."""
    import urllib.request

    from lodestar_tpu.metrics import MetricsRegistry, MetricsServer

    snap = {
        "enabled": True,
        "epochs_retained": 2,
        "max_rows": 64,
        "entries": [{"epoch": 7, "rows": 4, "device_resident": False}],
        "total_rows": 4,
        "evictions": 0,
        "device_put_failures": 0,
    }
    server = MetricsServer(MetricsRegistry(), port=0, epoch_table=lambda: snap)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/epoch_table"
        with urllib.request.urlopen(url) as r:
            assert json.load(r) == {"wired": True, **snap}
    finally:
        server.close()

    # knob off -> the verifier-side snapshot says enabled:false
    for snap_fn in (lambda: {"enabled": False}, lambda: None, None):
        server = MetricsServer(MetricsRegistry(), port=0, epoch_table=snap_fn)
        server.start()
        try:
            url = f"http://127.0.0.1:{server.port}/debug/epoch_table"
            with urllib.request.urlopen(url) as r:
                assert json.load(r) == {"wired": False}
        finally:
            server.close()

    def boom():
        raise RuntimeError("snapshot lock poisoned")

    server = MetricsServer(MetricsRegistry(), port=0, epoch_table=boom)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/epoch_table"
        try:
            urllib.request.urlopen(url)
            assert False, "expected 500"
        except urllib.error.HTTPError as e:
            assert e.code == 500
    finally:
        server.close()


# --- bench emitter -----------------------------------------------------------


def test_bench_emitter_phase_deadline_skips_gracefully(tmp_path, capsys):
    em = BenchEmitter(
        "m", "sets/s", baseline=100.0,
        details_path=str(tmp_path / "details.json"),
    )
    with em.phase("slow", deadline_s=0.05):
        while True:  # pure-Python spin: SIGALRM interrupts it
            time.sleep(0.005)
    with em.phase("broken"):
        raise RuntimeError("boom")
    with em.phase("good") as ph:
        ph.record("sets_per_sec", 50.0)
    em.set_headline(50.0)
    doc = em.emit()
    assert doc["phases"]["slow"]["status"] == "timeout"
    assert doc["phases"]["broken"]["status"] == "error"
    assert "boom" in doc["phases"]["broken"]["error"]
    assert doc["phases"]["good"]["status"] == "ok"
    assert doc["value"] == 50.0 and doc["vs_baseline"] == 0.5
    assert doc["partial"] is True  # two phases did not complete
    # stdout carries exactly one parseable JSON line; emit() is idempotent
    assert em.emit() is None
    line = capsys.readouterr().out.strip()
    assert json.loads(line)["value"] == 50.0
    on_disk = json.load(open(tmp_path / "details.json"))
    assert on_disk["phases"]["slow"]["status"] == "timeout"


def test_bench_emitter_headline_falls_back_to_best_phase_rate(capsys):
    em = BenchEmitter("m", "sets/s")
    with em.phase("a") as ph:
        ph.record("device_sets_per_sec", 123.0)
    doc = em.emit()
    capsys.readouterr()
    assert doc["value"] == 123.0  # never null, even without set_headline
    assert doc["partial"] is True


def test_bench_emitter_sections_evaluated_at_emit_time(capsys):
    p = PipelineMetrics()
    em = BenchEmitter("m", "sets/s")
    em.add_section("planner", p.planner_snapshot)
    p.planner("per_set", 7)  # AFTER registration, BEFORE emit
    doc = em.emit()
    capsys.readouterr()
    assert doc["planner"]["decisions"] == {"per_set": 1}


def test_bench_emitter_sigterm_flush():
    """The driver's `timeout` SIGTERMs a stuck bench; the handler must
    still print the structured document (the BENCH_r05 `parsed: null`
    regression guard)."""
    code = (
        "import sys, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
        "from lodestar_tpu.observability.bench_emit import BenchEmitter\n"
        "em = BenchEmitter('m', 'sets/s', baseline=10.0)\n"
        "with em.phase('spin') as ph:\n"
        "    ph.record('device_sets_per_sec', 5.0)\n"
        "    print('READY', flush=True)\n"
        "    while True:\n"
        "        time.sleep(0.02)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=20)
    finally:
        proc.kill()
    doc = json.loads(out.strip().splitlines()[-1])
    assert doc["phases"]["spin"]["status"] == "killed"
    assert doc["value"] == 5.0  # partial results survive the kill
    assert doc["partial"] is True
    # round 7: the kill is self-labelling so bench_compare can skip the
    # truncated round instead of gating its rates
    assert doc["timed_out"] is True


def test_bench_emitter_watchdog_thread_emits_when_main_thread_is_stuck():
    """The watchdog runs on its own thread, so it emits and exits even
    when the main thread sits in a call that signal handlers cannot
    interrupt (the XLA-compile-under-SIGTERM hole)."""
    code = (
        "import sys, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
        "from lodestar_tpu.observability.bench_emit import BenchEmitter\n"
        "em = BenchEmitter('m', 'sets/s', global_deadline_s=0.3)\n"
        "with em.phase('stuck'):\n"
        "    time.sleep(30)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    out, _ = proc.communicate(timeout=20)
    assert proc.returncode == 124
    doc = json.loads(out.strip().splitlines()[-1])
    assert doc["phases"]["stuck"]["status"] == "killed"
    assert doc["watchdog_fired_after_s"] == 0.3
    assert doc["timed_out"] is True


def test_check_dashboards_lint_passes():
    """tools/check_dashboards.py: zero dashboard metric names missing
    from the registry (ISSUE 1 acceptance)."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "check_dashboards.py",
    )
    spec = importlib.util.spec_from_file_location("check_dashboards", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


def test_check_dashboards_flags_unknown_metric(tmp_path, capsys):
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "check_dashboards.py",
    )
    spec = importlib.util.spec_from_file_location("check_dashboards2", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    bad = {
        "title": "t",
        "panels": [
            {"title": "p", "targets": [{"expr": "rate(lodestar_totally_made_up_total[1m])"}]}
        ],
    }
    (tmp_path / "bad.json").write_text(json.dumps(bad))
    assert mod.main(["check", str(tmp_path)]) == 1
    assert "lodestar_totally_made_up_total" in capsys.readouterr().out


def test_check_dashboards_flags_planted_slo_rules_violation(tmp_path, capsys):
    """ISSUE 16 satellite: the slo_rules lint catches an objective whose
    source metric no registry family declares, and a file under the
    committed-objectives floor (planted fixture)."""
    import importlib.util
    import shutil

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "check_dashboards.py",
    )
    spec = importlib.util.spec_from_file_location("check_dashboards3", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    dash_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "dashboards",
    )
    # real dashboards + the planted-violation rules file: only the rules
    # lint fires
    for name in os.listdir(dash_dir):
        if name != mod.SLO_RULES_FILE:
            shutil.copy(os.path.join(dash_dir, name), tmp_path / name)
    fixture = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "lint_fixtures", "slo_rules_bad.json",
    )
    shutil.copy(fixture, tmp_path / mod.SLO_RULES_FILE)
    assert mod.main(["check", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "SLO-RULES" in out
    assert "phantom_latency" in out
    assert "lodestar_bls_totally_made_up_seconds" in out
    assert "commits only 2 objectives" in out
    assert "SLO rules problem" in out
