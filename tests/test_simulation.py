"""Multi-node simulation: 4 nodes × 32 validators over real networking,
blocks via gossip only, finality within 4 epochs.

Reference analog: `cli/test/simulation/simulation.test.ts:18-90` — the
per-epoch assertions on missed blocks, heads, participation, finality.
"""

import asyncio

import pytest

# the simulation environment spins up live-networked nodes whose
# transport identities need the `cryptography` wheel — skip, not error
pytest.importorskip("cryptography")

from lodestar_tpu.sim import SimulationAssertions, SimulationEnvironment

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow



@pytest.fixture(scope="module")
def sim_result():
    async def main():
        # REAL signature verification end-to-end: the native C pairing
        # tier (round-3) is fast enough that the finalizing 4-node sim no
        # longer needs MockBlsVerifier (VERDICT r2 weak #5)
        env = SimulationEnvironment(
            n_nodes=4, n_validators=32, verifier="cpu"
        )
        await env.start()
        try:
            await env.run_epochs(4)
        finally:
            await env.stop()
        return env

    return asyncio.run(asyncio.wait_for(main(), 600))


def test_sim_no_missed_blocks(sim_result):
    SimulationAssertions.assert_no_missed_blocks(sim_result)


def test_sim_heads_consistent_across_nodes(sim_result):
    SimulationAssertions.assert_heads_consistent(sim_result)


def test_sim_finalizes(sim_result):
    # justification needs 2 full epochs of attestations; finality trails by
    # one more — after 4 epochs a healthy chain has finalized >= epoch 1
    SimulationAssertions.assert_finalization(sim_result, min_final=1)


def test_sim_participation(sim_result):
    SimulationAssertions.assert_participation(sim_result, minimum=0.5)


def test_sim_blocks_propagated_via_gossip_only(sim_result):
    """Every node imported every block; only the proposer called
    process_block locally — the rest came through gossip validation."""
    env = sim_result
    head = env.nodes[0].chain.head_root
    for node in env.nodes[1:]:
        assert node.chain.head_root == head
        assert node.chain.fork_choice.has_block(head)


def test_sim_two_nodes_with_device_verifier():
    """VERDICT round-1 weak #5: at least one sim config must exercise the
    REAL device batch verifier in the end-to-end loop (2 nodes × 8
    validators × 1 epoch on the virtual CPU mesh, small buckets — every
    gossip block/aggregate goes through TpuBlsVerifier kernels)."""

    async def main():
        env = SimulationEnvironment(n_nodes=2, n_validators=8, verifier="device")
        await env.start()
        try:
            await env.run_epochs(1)
        finally:
            await env.stop()
        return env

    env = asyncio.run(asyncio.wait_for(main(), 2400))
    # liveness through real crypto: blocks were produced, and EVERY node
    # imported gossiped blocks through the device batch kernels (exact
    # head agreement within one epoch is too strict at ~seconds/verify
    # on this 1-core box — the mock-verifier sim asserts convergence)
    assert env.blocks_produced > 0
    for node in env.nodes:
        assert node.chain.head_state.state.slot > 0, "node never imported"
        # cross-node proof: this node holds a block whose PROPOSER lives
        # on the other node — it can only have arrived via gossip through
        # the device-verifier validation pipeline
        foreign = [
            signed
            for signed in node.chain.blocks.values()
            if signed is not None
            and int(signed.message.proposer_index) not in node.key_range
        ]
        assert foreign, f"node {node.index} imported no gossiped blocks"

