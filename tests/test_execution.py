"""Execution engine tests: mock EL block tree + payload building, JWT
format (reference: engine/mock e2e usage + http client unit behavior)."""

import base64
import hashlib
import hmac
import json

from lodestar_tpu.execution import (
    ExecutePayloadStatus,
    ExecutionEngineMock,
    PayloadAttributes,
)
from lodestar_tpu.execution.engine import _jwt_hs256, _MockPayload


def test_mock_el_build_and_import_flow():
    el = ExecutionEngineMock()
    genesis = b"\x00" * 32
    # start building on genesis
    pid = el.notify_forkchoice_update(
        genesis, genesis, genesis,
        PayloadAttributes(
            timestamp=12, prev_randao=b"\x01" * 32, suggested_fee_recipient=b"\x02" * 20
        ),
    )
    assert pid is not None
    payload = el.get_payload(pid)
    assert payload.block_number == 1
    assert payload.parent_hash == genesis

    # import it back
    assert el.notify_new_payload(payload) == ExecutePayloadStatus.VALID
    assert el.notify_forkchoice_update(payload.block_hash, genesis, genesis) is None
    assert el.head == payload.block_hash

    # unknown parent → SYNCING
    orphan = _MockPayload(
        block_hash=b"\x09" * 32, parent_hash=b"\x08" * 32, block_number=9,
        timestamp=0, prev_randao=b"\x00" * 32, fee_recipient=b"\x00" * 20,
    )
    assert el.notify_new_payload(orphan) == ExecutePayloadStatus.SYNCING

    # injected invalid hash → INVALID
    el.invalid_hashes.add(b"\x0a" * 32)
    bad = _MockPayload(
        block_hash=b"\x0a" * 32, parent_hash=payload.block_hash, block_number=2,
        timestamp=13, prev_randao=b"\x00" * 32, fee_recipient=b"\x00" * 20,
    )
    assert el.notify_new_payload(bad) == ExecutePayloadStatus.INVALID


def test_payload_ids_are_single_use():
    el = ExecutionEngineMock()
    g = b"\x00" * 32
    pid = el.notify_forkchoice_update(
        g, g, g, PayloadAttributes(1, b"\x00" * 32, b"\x00" * 20)
    )
    el.get_payload(pid)
    try:
        el.get_payload(pid)
        assert False, "payload id must be single-use"
    except ValueError:
        pass


def test_jwt_hs256_shape():
    secret = b"\x42" * 32
    token = _jwt_hs256(secret)
    header_b64, claims_b64, sig_b64 = token.split(".")
    pad = lambda s: s + "=" * (-len(s) % 4)
    header = json.loads(base64.urlsafe_b64decode(pad(header_b64)))
    claims = json.loads(base64.urlsafe_b64decode(pad(claims_b64)))
    assert header == {"alg": "HS256", "typ": "JWT"}
    assert "iat" in claims
    expected = hmac.new(
        secret, f"{header_b64}.{claims_b64}".encode(), hashlib.sha256
    ).digest()
    assert base64.urlsafe_b64decode(pad(sig_b64)) == expected
