"""Pallas Montgomery-mul kernel vs the XLA path and the CPU oracle.

The kernel runs under the Pallas interpreter on the CPU backend (same
kernel body that compiles for TPU) — differential over random field
elements in the lazy-reduction domain [0, 2p).
"""

import numpy as np
import pytest

from lodestar_tpu.bls.fields import P
from lodestar_tpu.ops import fp
from lodestar_tpu.ops.limbs import N_LIMBS, R_MONT, int_to_limbs, limbs_to_int
from lodestar_tpu.ops.pallas_fp import LANES, mont_mul

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow



def _rand_elems(rng, n, bound):
    vals = [rng.randrange(bound) for _ in range(n)]
    arr = np.stack([int_to_limbs(v) for v in vals])
    return vals, arr


def test_pallas_mul_matches_xla_path():
    import random

    rng = random.Random(42)
    vals_a, a = _rand_elems(rng, 40, 2 * P)
    vals_b, b = _rand_elems(rng, 40, 2 * P)
    got = np.asarray(mont_mul(a, b, interpret=True))
    want = np.asarray(fp.mul(a, b))
    assert got.shape == want.shape == (40, N_LIMBS)
    assert np.array_equal(got, want)


def test_pallas_mul_matches_bigint_oracle():
    import random

    rng = random.Random(7)
    vals_a, a = _rand_elems(rng, 8, P)
    vals_b, b = _rand_elems(rng, 8, P)
    got = np.asarray(mont_mul(a, b, interpret=True))
    r_inv = pow(R_MONT, -1, P)
    for i in range(8):
        # REDC(a*b) = a*b*R^-1 mod p, up to one extra p (lazy reduction)
        value = limbs_to_int(got[i])
        expect = (vals_a[i] * vals_b[i] * r_inv) % P
        assert value % P == expect
        assert value < 2 * P


def test_pallas_mul_batch_padding_and_broadcast():
    import random

    rng = random.Random(9)
    # batch sizes around the 128-lane tile boundary, incl. broadcasting
    for n in (1, LANES - 1, LANES, LANES + 3):
        _, a = _rand_elems(rng, n, 2 * P)
        _, b = _rand_elems(rng, 1, 2 * P)
        got = np.asarray(mont_mul(a, b[0], interpret=True))
        want = np.asarray(fp.mul(a, b[0]))
        assert np.array_equal(got, want), f"batch {n}"


def test_pallas_mul_multi_axis_batch():
    import random

    rng = random.Random(11)
    _, a = _rand_elems(rng, 12, 2 * P)
    _, b = _rand_elems(rng, 12, 2 * P)
    a3 = a.reshape(3, 4, N_LIMBS)
    b3 = b.reshape(3, 4, N_LIMBS)
    got = np.asarray(mont_mul(a3, b3, interpret=True))
    want = np.asarray(fp.mul(a3, b3))
    assert got.shape == (3, 4, N_LIMBS)
    assert np.array_equal(got, want)


def test_mxu_mul_matches_oracle():
    """Experimental MXU-mapped Montgomery mul (ops/mxu_fp.py): exact
    against the big-int oracle and bit-compatible with fp.mul's domain."""
    import random

    import numpy as np

    from lodestar_tpu.ops import mxu_fp
    from lodestar_tpu.ops.limbs import R_MONT, int_to_limbs, limbs_to_int

    rng = random.Random(23)
    n = 10
    a_vals = [rng.randrange(2 * P) for _ in range(n)]
    b_vals = [rng.randrange(2 * P) for _ in range(n)]
    a = np.stack([int_to_limbs(v) for v in a_vals])
    b = np.stack([int_to_limbs(v) for v in b_vals])
    got = np.asarray(mxu_fp.mul(a, b))
    r_inv = pow(R_MONT, -1, P)
    for i in range(n):
        value = limbs_to_int(got[i])
        assert value < 2 * P
        assert value % P == (a_vals[i] * b_vals[i] * r_inv) % P


def test_mxu_carry_lookahead_matches_scan():
    """Log-depth carry propagation ≡ the sequential scan, including the
    adversarial full-ripple case."""
    import numpy as np

    from lodestar_tpu.ops import mxu_fp

    rng = np.random.default_rng(4)
    t = rng.integers(0, 1 << 30, size=(5, 64), dtype=np.int64).astype(np.int32)
    a, _ = mxu_fp._carry(t)
    b, _ = mxu_fp._carry_scan(t)
    assert np.array_equal(np.asarray(a), np.asarray(b))
    ripple = np.full((1, 64), (1 << 12) - 1, np.int32)
    ripple[0, 0] = 1 << 12
    a, _ = mxu_fp._carry(ripple)
    b, _ = mxu_fp._carry_scan(ripple)
    assert np.array_equal(np.asarray(a), np.asarray(b))
