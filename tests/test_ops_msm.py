"""Differential tests for the bit-plane MSM tier (`ops/msm.py`) and the ψ
endomorphism against the big-int oracle.

These run eagerly at tiny shapes — point ops only, no pairing compiles —
so they live in the fast suite. Projective equality (`CurveOps.eq`) avoids
the Fermat inversion of `to_affine`.
"""

import numpy as np
import pytest

from lodestar_tpu.bls.curve import PointG1, PointG2
from lodestar_tpu.bls.fields import R as ORDER
from lodestar_tpu.bls.fields import X_PARAM
from lodestar_tpu.ops import fp, fp2, msm
from lodestar_tpu.ops.io_host import g1_affine_to_limbs, g2_affine_to_limbs
from lodestar_tpu.ops.points import g1, g2, g2_psi

import jax.numpy as jnp


def _host_g1(i: int) -> PointG1:
    return PointG1.generator() * (i * 7919 + 13)


def _host_g2(i: int) -> PointG2:
    return PointG2.generator() * (i * 104729 + 7)


def _dev_g1(points):
    xs, ys = zip(*((g1_affine_to_limbs(p)[:2]) for p in points))
    return (
        jnp.asarray(np.stack(xs)),
        jnp.asarray(np.stack(ys)),
        fp.one((len(points),)),
    )


def _dev_g2(points):
    xs, ys = zip(*((g2_affine_to_limbs(p)[:2]) for p in points))
    return (
        jnp.asarray(np.stack(xs)),
        jnp.asarray(np.stack(ys)),
        fp2.one((len(points),)),
    )


def _assert_is_g1(dev_point, host_point):
    if host_point.is_infinity():
        assert bool(g1.is_infinity(dev_point))
        return
    x, y, _ = g1_affine_to_limbs(host_point)
    want = (jnp.asarray(x), jnp.asarray(y), fp.one(()))
    assert bool(g1.eq(dev_point, want))


def _assert_is_g2(dev_point, host_point):
    if host_point.is_infinity():
        assert bool(g2.is_infinity(dev_point))
        return
    x, y, _ = g2_affine_to_limbs(host_point)
    want = (jnp.asarray(x), jnp.asarray(y), fp2.one(()))
    assert bool(g2.eq(dev_point, want))


# eager point-op dispatch is ~minutes in aggregate on the CPU backend —
# the heavy differential tests ride the slow suite (fast-suite budget
# is <5 min cold-cache, VERDICT r2 weak #3)
_heavy = pytest.mark.slow


@_heavy
def test_tree_sum_matches_oracle():
    pts = [_host_g1(i) for i in range(5)]
    dev = _dev_g1(pts)
    got = msm.tree_sum(g1, dev)
    _assert_is_g1(got, sum(pts[1:], pts[0]))


@_heavy
def test_subset_table4_all_masks():
    pts = [_host_g1(i) for i in range(4)]
    dev = tuple(c[None] for c in _dev_g1(pts))  # (1, 4, …)
    table = msm.subset_table4(g1, dev)
    for mask in range(16):
        want = PointG1.zero()
        for k in range(4):
            if mask & (1 << k):
                want = want + pts[k]
        got = tuple(c[0, mask] for c in table)
        _assert_is_g1(got, want)


@_heavy
def test_masked_plane_sums_g1():
    rng = np.random.default_rng(42)
    pts = [_host_g1(i) for i in range(8)]
    bits = rng.integers(0, 2, size=(8, 5)).astype(np.int32)
    planes = msm.masked_plane_sums(g1, _dev_g1(pts), jnp.asarray(bits))
    for t in range(5):
        want = PointG1.zero()
        for l in range(8):
            if bits[l, t]:
                want = want + pts[l]
        _assert_is_g1(tuple(c[t] for c in planes), want)


@_heavy
def test_masked_plane_sums_g2():
    rng = np.random.default_rng(7)
    pts = [_host_g2(i) for i in range(4)]
    bits = rng.integers(0, 2, size=(4, 3)).astype(np.int32)
    planes = msm.masked_plane_sums(g2, _dev_g2(pts), jnp.asarray(bits))
    for t in range(3):
        want = PointG2.zero()
        for l in range(4):
            if bits[l, t]:
                want = want + pts[l]
        _assert_is_g2(tuple(c[t] for c in planes), want)


@_heavy
def test_horner_pow2_recombines_scalar():
    k = 0x9E3779B9  # 32-bit
    p = _host_g1(3)
    x, y, _ = g1_affine_to_limbs(p)
    px = jnp.broadcast_to(jnp.asarray(x), (32, 32))
    py = jnp.broadcast_to(jnp.asarray(y), (32, 32))
    sel = jnp.asarray(np.array([(k >> t) & 1 for t in range(32)], bool))
    planes = g1.select(sel, (px, py, fp.one((32,))), g1.infinity((32,)))
    _assert_is_g1(msm.horner_pow2(g1, planes), p * k)


@_heavy
def test_g2_psi_matches_oracle_and_z_mul():
    q = _host_g2(11)
    dev = tuple(c[0] for c in _dev_g2([q]))
    got = g2_psi(dev)
    assert q.psi() == q * (X_PARAM % ORDER)  # eigenvalue sanity
    _assert_is_g2(got, q.psi())


@_heavy
def test_g2_psi_preserves_infinity():
    inf = g2.infinity(())
    assert bool(g2.is_infinity(g2_psi(inf)))


def test_gls_split_soundness_identity():
    """r·Q == a·Q + ψ(b·Q) for r = a + z·b — the grouped kernel's algebra."""
    a, b = 0xDEADBEEF, 0x12345678
    r = (a + X_PARAM * b) % ORDER
    q = _host_g2(5)
    assert q * r == q * a + (q * b).psi()
