"""AOT executable store: restart without XLA in the loop (ISSUE 19).

PR 11 measured 168.1 s cold serving-ready vs 33.7 s with a warm trace
cache; these tests pin the layer that removes XLA from the restart path
entirely: serialized executables round-trip through the on-disk store,
load-before-compile serves them under the `aot_hit` classification, and
— the robustness half — every corruption mode (truncate, bit-flip,
foreign build fingerprint, partial write, format bump) degrades to a
normal JIT compile with the right outcome counter and a flight-recorder
event, never a crash and never a silently wrong executable. The slow
tier holds the subprocess cold-restart round trip for the production
grouped 16x8 shape with the `serving_ready_seconds <= 10 s` acceptance
gate, and the evicted-mesh re-dispatch that serves a pre-exported shrunk
chip set with zero new compile events.
"""

import json
import os
import struct
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from lodestar_tpu.observability.compile_ledger import (  # noqa: E402
    CompileLedger,
    timeline,
)
from lodestar_tpu.observability.flight_recorder import recorder  # noqa: E402
from lodestar_tpu.observability.stages import PipelineMetrics  # noqa: E402
from lodestar_tpu.ops import aot_store  # noqa: E402


@pytest.fixture
def store_root(tmp_path, monkeypatch):
    root = str(tmp_path / "aot")
    monkeypatch.setenv("LODESTAR_TPU_AOT_STORE", root)
    monkeypatch.delenv("LODESTAR_TPU_AOT_EXPORT", raising=False)
    monkeypatch.delenv("LODESTAR_TPU_AOT_LOAD", raising=False)
    aot_store.reset_for_tests()
    yield root
    aot_store.reset_for_tests()


def _export_tiny(kernel, monkeypatch, body=None):
    """Compile + export one tiny jitted kernel through the ledger's
    producer path; returns (artifact_path, expected_output_fn)."""
    import jax
    import jax.numpy as jnp

    body = body or (lambda x: x * 2 + 1)
    monkeypatch.setenv("LODESTAR_TPU_AOT_EXPORT", "1")
    led = CompileLedger()
    fn = led.wrap(jax.jit(body), kernel)
    out = fn(jnp.arange(8.0))
    monkeypatch.setenv("LODESTAR_TPU_AOT_EXPORT", "0")
    st = aot_store.store()
    path = st.path_for(kernel, "float32[8]")
    assert os.path.exists(path), "export must persist the artifact"
    return path, out


def _consume(kernel, body=None):
    """Fresh ledger + pipeline, one wrapped call; returns
    (output, ledger, pipeline)."""
    import jax
    import jax.numpy as jnp

    body = body or (lambda x: x * 2 + 1)
    led = CompileLedger()
    p = PipelineMetrics()
    led.attach(p)
    fn = led.wrap(jax.jit(body), kernel)
    return fn(jnp.arange(8.0)), led, p


def _rewrite_header(path, mutate):
    with open(path, "rb") as f:
        raw = f.read()
    (hlen,) = struct.unpack(">I", raw[8:12])
    header = json.loads(raw[12:12 + hlen])
    payload = raw[12 + hlen:]
    mutate(header)
    hb = json.dumps(header, sort_keys=True).encode()
    with open(path, "wb") as f:
        f.write(raw[:8] + struct.pack(">I", len(hb)) + hb + payload)


# -- round trip -------------------------------------------------------------


def test_export_writes_checksummed_artifact(store_root, monkeypatch):
    path, _ = _export_tiny("t_aot_export", monkeypatch)
    st = aot_store.store()
    header = st.read_header(path)
    assert header["kernel"] == "t_aot_export"
    assert header["key"] == "float32[8]"
    assert header["fingerprint"] == st.current_fingerprint()
    assert header["payload_len"] > 0 and len(header["payload_sha256"]) == 64
    # atomic write-then-rename: no tmp residue next to the artifact
    assert all(not n.endswith(".tmp") for n in os.listdir(store_root))
    (entry,) = st.entries()
    assert entry["kernel"] == "t_aot_export" and entry["bytes"] > 0


def test_load_bypasses_jit_and_classifies_aot_hit(store_root, monkeypatch):
    import numpy as np

    _export_tiny("t_aot_roundtrip", monkeypatch)
    # consumer wraps a DIFFERENT body: a served result matching the
    # EXPORTED semantics proves the dispatch never entered the jitted fn
    out, led, p = _consume("t_aot_roundtrip", body=lambda x: x * 1000)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 2 + 1)
    snap = led.snapshot()
    assert snap["aot"]["counts"] == {"hit": 1}
    assert snap["cache"]["aot_hits"] == 1
    assert [e["cache"] for e in snap["events"]] == ["aot_hit"]
    assert snap["aot"]["loaded_executables"] == 1
    text = p.registry.expose()
    assert ('lodestar_tpu_aot_events_total{kernel="t_aot_roundtrip",'
            'outcome="hit"} 1.0') in text
    # the startup timeline gained the aot_load phase on the first hit
    assert any(m["phase"] == "aot_load"
               for m in timeline().snapshot()["marks"])
    kinds = [e["kind"] for e in recorder().dump()["events"]]
    assert "aot" in kinds


def test_preload_loads_current_fingerprint_only(store_root, monkeypatch):
    import numpy as np

    path, _ = _export_tiny("t_aot_preload", monkeypatch)
    # a second artifact from a foreign build must be skipped (counted as
    # version_mismatch), not loaded
    foreign = path.replace(".aot", "_foreign.aot")
    import shutil

    shutil.copy(path, foreign)
    _rewrite_header(
        foreign, lambda h: h["fingerprint"].update({"jaxlib": "0.0.0"})
    )
    led = CompileLedger()
    summary = led.preload_aot()
    assert summary["loaded"] == ["t_aot_preload:float32[8]"]
    assert summary["skipped"] == 1
    snap = led.snapshot()
    assert snap["aot"]["counts"]["hit"] == 1
    assert snap["aot"]["counts"]["version_mismatch"] == 1
    # the preloaded executable serves without the wrapped fn compiling
    import jax

    fn = led.wrap(jax.jit(lambda x: x * -1), "t_aot_preload")
    out = fn(np.arange(8.0).astype(np.float32))
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 2 + 1)


def test_store_disabled_and_load_off_are_inert(store_root, monkeypatch):
    _export_tiny("t_aot_gates", monkeypatch)
    # LOAD=0: populated store, but the consumer compiles normally
    monkeypatch.setenv("LODESTAR_TPU_AOT_LOAD", "0")
    out, led, _ = _consume("t_aot_gates")
    snap = led.snapshot()
    assert snap["aot"]["counts"] == {}
    assert snap["events"][0]["cache"] in ("hit", "miss", "off")
    # STORE=off: store() resolves to None everywhere
    monkeypatch.setenv("LODESTAR_TPU_AOT_STORE", "off")
    monkeypatch.delenv("LODESTAR_TPU_AOT_LOAD", raising=False)
    assert aot_store.store() is None
    out2, led2, _ = _consume("t_aot_gates2")
    assert led2.snapshot()["aot"]["counts"] == {}
    assert led2.preload_aot()["loaded"] == []


# -- corruption fuzz --------------------------------------------------------


def _truncate(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(size - 100)


def _bit_flip(path):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - 50)  # inside the payload
        b = f.read(1)
        f.seek(size - 50)
        f.write(bytes([b[0] ^ 0x40]))


def _wrong_fingerprint(path):
    _rewrite_header(path, lambda h: h["fingerprint"].update({"jax": "0.0.0"}))


def _partial_write(path):
    # crash mid-write of a NON-atomic writer: magic + half a header
    with open(path, "wb") as f:
        f.write(aot_store.MAGIC + struct.pack(">I", 400) + b"{\"ker")


def _bad_magic(path):
    with open(path, "r+b") as f:
        f.write(b"GARBAGE!")


def _future_format(path):
    with open(path, "r+b") as f:
        f.write(aot_store.MAGIC[:-1] + b"9")


CORRUPTIONS = [
    (_truncate, "corrupt"),
    (_bit_flip, "corrupt"),
    (_wrong_fingerprint, "version_mismatch"),
    (_partial_write, "corrupt"),
    (_bad_magic, "corrupt"),
    (_future_format, "version_mismatch"),
]


@pytest.mark.parametrize("mutate,outcome", CORRUPTIONS,
                         ids=[m.__name__.lstrip("_") for m, _ in CORRUPTIONS])
def test_corruption_degrades_to_jit(store_root, monkeypatch, mutate, outcome):
    """Every artifact failure mode falls back to a normal (correct!) JIT
    compile with the right outcome counter and a flight event — the
    acceptance criterion: no crash, no silent wrong executable."""
    import numpy as np

    kernel = f"t_aot_fuzz_{mutate.__name__.lstrip('_')}"
    path, _ = _export_tiny(kernel, monkeypatch)
    mutate(path)
    out, led, p = _consume(kernel)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 2 + 1)
    snap = led.snapshot()
    assert snap["aot"]["counts"] == {outcome: 1}
    # the fallback is a REAL compile event, classified by the trace cache
    assert [e["cache"] for e in snap["events"]] != ["aot_hit"]
    assert snap["events"][0]["cache"] in ("hit", "miss", "off")
    assert (f'outcome="{outcome}"') in p.registry.expose()
    aot_events = [e for e in recorder().dump()["events"]
                  if e["kind"] == "aot" and e.get("kernel") == kernel]
    assert aot_events and aot_events[-1]["outcome"] == outcome


# -- mesh seam --------------------------------------------------------------


def _tiny_mesh_factory():
    """A stub sharded-verifier factory whose `_run` is a real jitted fn —
    the wrap seam and AOT export/load flow are exactly the production
    ones, without the minutes-long shard_map compiles."""
    import jax
    import jax.numpy as jnp

    run = jax.jit(lambda x: (x.sum() * 0 + 1).astype(jnp.int32))

    class _Stub:
        def __init__(self):
            self._run = run

        def submit(self, g, a_bits, b_bits):
            return self._run(g.pk_x)

    return lambda kind, devices, axis: _Stub()


def test_mesh_seam_prefers_jitted_run(store_root):
    from lodestar_tpu.parallel.mesh import _ledger_wrap_submit

    v = _tiny_mesh_factory()("grouped", [0, 1], "dp")
    _ledger_wrap_submit(v, "grouped", (4, 2), (0, 1))
    # the jit entry (with .lower — the AOT seam) got the wrap, the
    # submit facade stayed untouched (still the class method, unwrapped)
    assert v._run.__compile_ledger_kernel__ == "sharded_grouped"
    assert not hasattr(v.submit, "__compile_ledger_kernel__")
    assert "submit" not in vars(v)


def test_evicted_mesh_redispatch_serves_from_aot(store_root, monkeypatch):
    """The acceptance criterion: an evicted-mesh re-dispatch for an
    already-exported shrunk chip set completes with `aot_hit` and ZERO
    new compile events — the post-eviction recompile-on-the-serving-path
    cost (ROADMAP item 2) is gone when the producer exported that chip
    set."""
    import types

    import numpy as np

    import lodestar_tpu.observability.compile_ledger as cl
    from lodestar_tpu.parallel.mesh import BlsMeshDispatcher

    g = types.SimpleNamespace(pk_x=np.ones((4, 2, 3), np.float32))

    def dispatch_both_sizes(dispatcher):
        out_full = dispatcher.dispatch_grouped(g, None, None)
        dispatcher.evict(reason="test")
        out_shrunk = dispatcher.dispatch_grouped(g, None, None)
        return out_full, out_shrunk

    # producer: export the full AND the post-eviction chip set
    monkeypatch.setenv("LODESTAR_TPU_AOT_EXPORT", "1")
    monkeypatch.setattr(cl, "_ledger", CompileLedger())
    d1 = BlsMeshDispatcher(
        ["c0", "c1", "c2", "c3"], verifier_factory=_tiny_mesh_factory()
    )
    dispatch_both_sizes(d1)
    assert cl.ledger().snapshot()["aot"]["counts"]["export"] == 2

    # restarted consumer: fresh ledger, fresh dispatcher, load-only
    monkeypatch.setenv("LODESTAR_TPU_AOT_EXPORT", "0")
    monkeypatch.setattr(cl, "_ledger", CompileLedger())
    d2 = BlsMeshDispatcher(
        ["c0", "c1", "c2", "c3"], verifier_factory=_tiny_mesh_factory()
    )
    out_full, out_shrunk = dispatch_both_sizes(d2)
    assert int(out_full) == 1 and int(out_shrunk) == 1
    snap = cl.ledger().snapshot()
    assert snap["aot"]["counts"] == {"hit": 2}
    # zero NEW compiles: every ledger event this process is an aot_hit
    assert [e["cache"] for e in snap["events"]] == ["aot_hit", "aot_hit"]
    assert {e["key"] for e in snap["events"]} == {
        "(4, 2)@chips0,1,2,3", "(4, 2)@chips0,1",
    }


# -- shared prune budget ----------------------------------------------------


def test_prune_shared_budget_covers_aot_store(store_root, tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "prune_compile_cache",
        os.path.join(REPO_ROOT, "tools", "prune_compile_cache.py"),
    )
    pcc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pcc)

    cache = tmp_path / "jax_cache"
    cache.mkdir()
    os.makedirs(store_root, exist_ok=True)
    mb = 1 << 20

    def make(directory, name, size_mb, age):
        path = os.path.join(str(directory), name)
        with open(path, "wb") as f:
            f.write(b"\0" * (size_mb * mb))
        os.utime(path, (1_000_000_000 + age, 1_000_000_000 + age))
        return path

    oldest = make(cache, "trace_old", 4, age=0)
    old_aot = make(store_root, "k-aaaa.aot", 4, age=10)
    newer = make(cache, "trace_new", 4, age=20)
    newest_aot = make(store_root, "k-bbbb.aot", 4, age=30)

    r = pcc.prune(str(cache), limit_gb=9 * mb / (1 << 30),
                  aot_dir=store_root)
    # ONE LRU order across both dirs: the two oldest go, regardless of dir
    assert r["removed"] == [oldest, old_aot]
    assert r["aot_removed"] == 1
    assert sorted(r["dirs"]) == sorted([str(cache), store_root])
    assert os.path.exists(newer) and os.path.exists(newest_aot)


# -- subprocess cold restart (the acceptance number) ------------------------


PRODUCER = """
import json, os, sys
from lodestar_tpu.parallel.verifier import BatchVerifier
from lodestar_tpu.utils.jax_env import enable_compile_cache
import __graft_entry__
enable_compile_cache()
bv = BatchVerifier(grouped_configs=((16, 8),))
g, a_bits, b_bits = __graft_entry__._example_grouped(16, 8)
ok = bool(bv.verify_grouped(g, a_bits, b_bits))
from lodestar_tpu.observability.compile_ledger import ledger
print(json.dumps({"ok": ok, "aot": ledger().snapshot()["aot"]["counts"]}))
"""

CONSUMER = """
import json
# the restart path the node takes: ledger + verifier construction, AOT
# preload, serving-ready mark — executables resident, XLA never entered
from lodestar_tpu.observability.compile_ledger import ledger, timeline
from lodestar_tpu.parallel.verifier import BatchVerifier
bv = BatchVerifier(grouped_configs=((16, 8),))
summary = ledger().preload_aot()
t_ready = timeline().mark_serving_ready()
# correctness check OUTSIDE the SLO window: the loaded executable must
# produce the true verdict (workload latency, not startup)
import __graft_entry__
g, a_bits, b_bits = __graft_entry__._example_grouped(16, 8)
ok = bool(bv.verify_grouped(g, a_bits, b_bits))
snap = ledger().snapshot()
print(json.dumps({
    "serving_ready_s": t_ready,
    "loaded": summary["loaded"],
    "ok": ok,
    "aot": snap["aot"]["counts"],
    "caches": [e["cache"] for e in snap["events"]],
}))
"""


@pytest.mark.slow
def test_cold_restart_round_trip_serving_ready_slo(tmp_path):
    """Producer subprocess exports the production grouped 16x8 executable;
    a fresh consumer process loads it from disk and must be serving-ready
    within the 10 s SLO (vs the measured 33.7 s warm-trace-cache and
    168.1 s cold baselines, docs/architecture.md) — with the dispatch
    classified aot_hit and no compile event."""
    store = str(tmp_path / "aot")
    base_env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "LODESTAR_TPU_AOT_STORE": store,
        "PYTHONPATH": REPO_ROOT,
    }

    producer = subprocess.run(
        [sys.executable, "-c", PRODUCER],
        env={**base_env, "LODESTAR_TPU_AOT_EXPORT": "1"},
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=840,
    )
    assert producer.returncode == 0, producer.stderr[-2000:]
    pdoc = json.loads(producer.stdout.strip().splitlines()[-1])
    assert pdoc["ok"] and pdoc["aot"].get("export", 0) >= 1

    consumer = subprocess.run(
        [sys.executable, "-c", CONSUMER],
        env=base_env, cwd=REPO_ROOT,
        capture_output=True, text=True, timeout=300,
    )
    assert consumer.returncode == 0, consumer.stderr[-2000:]
    doc = json.loads(consumer.stdout.strip().splitlines()[-1])
    assert doc["ok"] is True
    assert doc["loaded"], "consumer must load the persisted executable"
    assert doc["aot"].get("hit", 0) >= 1 and "miss" not in doc["aot"]
    assert doc["caches"] and all(c == "aot_hit" for c in doc["caches"]), (
        f"restart must not compile: {doc['caches']}"
    )
    assert doc["serving_ready_s"] <= 10.0, (
        f"serving-ready {doc['serving_ready_s']:.1f}s blows the 10 s SLO"
    )
