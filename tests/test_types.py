"""Per-fork container type tests (reference analog: types package tests +
ssz_static structural checks)."""

import pytest

from lodestar_tpu.params import MAINNET, MINIMAL
from lodestar_tpu.types import get_types


@pytest.fixture(scope="module")
def t():
    return get_types(MINIMAL)


def test_state_field_evolution(t):
    phase0_fields = [n for n, _ in t.phase0.BeaconState.fields]
    altair_fields = [n for n, _ in t.altair.BeaconState.fields]
    capella_fields = [n for n, _ in t.capella.BeaconState.fields]
    assert "previous_epoch_attestations" in phase0_fields
    assert "previous_epoch_attestations" not in altair_fields
    assert "previous_epoch_participation" in altair_fields
    assert altair_fields[-3:] == [
        "inactivity_scores",
        "current_sync_committee",
        "next_sync_committee",
    ]
    assert capella_fields[-3:] == [
        "next_withdrawal_index",
        "next_withdrawal_validator_index",
        "historical_summaries",
    ]
    # phase0 prefix is preserved in order
    assert altair_fields[: phase0_fields.index("previous_epoch_attestations")] == phase0_fields[
        : phase0_fields.index("previous_epoch_attestations")
    ]


def test_default_state_roundtrip_all_forks(t):
    for fork in ("phase0", "altair", "bellatrix", "capella"):
        ns = getattr(t, fork)
        state = ns.BeaconState.default()
        data = state.serialize()
        state2 = ns.BeaconState.deserialize(data)
        assert state2 == state
        assert state.hash_tree_root() == state2.hash_tree_root()


def test_fork_roots_differ(t):
    r = {
        fork: getattr(t, fork).BeaconState.default().hash_tree_root()
        for fork in ("phase0", "altair", "bellatrix", "capella")
    }
    assert len(set(r.values())) == 4


def test_signed_block_roundtrip(t):
    block = t.capella.SignedBeaconBlock.default()
    block.message.slot = 42
    block.message.body.graffiti = b"lodestar-tpu".ljust(32, b"\x00")
    block.message.body.attestations = [
        t.phase0.Attestation(
            aggregation_bits=[True, False, True],
            signature=b"\xaa" * 96,
        )
    ]
    data = block.serialize()
    block2 = t.capella.SignedBeaconBlock.deserialize(data)
    assert block2 == block
    assert block2.message.body.attestations[0].aggregation_bits == [True, False, True]


def test_validator_fixed_size(t):
    v = t.phase0.Validator.ssz_type
    assert v.is_fixed_size()
    assert v.fixed_size() == 121  # 48+32+8+1+8+8+8+8


def test_mainnet_vs_minimal_types_differ():
    tm = get_types(MAINNET)
    tmin = get_types(MINIMAL)
    # sync committee sizes differ -> serialized sizes differ
    assert len(tm.altair.SyncCommittee.default().serialize()) != len(
        tmin.altair.SyncCommittee.default().serialize()
    )


def test_execution_payload_capella_withdrawals(t):
    p = t.capella.ExecutionPayload.default()
    p.withdrawals = [t.capella.Withdrawal(index=1, validator_index=2, address=b"\x11" * 20, amount=3)]
    data = p.serialize()
    p2 = t.capella.ExecutionPayload.deserialize(data)
    assert p2.withdrawals[0].amount == 3


def test_light_client_types(t):
    upd = t.altair.LightClientUpdate.default()
    assert len(upd.finality_branch) == 6
    assert len(upd.next_sync_committee_branch) == 5
    data = upd.serialize()
    assert t.altair.LightClientUpdate.deserialize(data) == upd
