"""Device-time & memory ledger tests (ISSUE 16 tentpole B): interval
attribution through the real lane dispatcher (>= 95% of device wall
time accounted), nested-dispatch double-count suppression, overlap
accounting, memory-watermark monotonicity, the /debug/device endpoint,
and the rc=124 post-mortem inclusion (the watchdog's emission must
carry the device section).
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from lodestar_tpu.chain.bls_verifier import MockBlsVerifier
from lodestar_tpu.chain.dispatcher import BlsLaneDispatcher
from lodestar_tpu.observability import device_ledger
from lodestar_tpu.observability.device_ledger import DeviceLedger
from lodestar_tpu.observability.stages import PipelineMetrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_ledger():
    device_ledger._reset_for_tests()
    yield
    device_ledger._reset_for_tests()


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# --- interval attribution -----------------------------------------------------


def test_lane_flush_attributes_stub_verifier_time():
    clock = FakeClock()
    led = DeviceLedger(clock=clock)
    with led.lane_flush("block"):
        clock.advance(0.25)
    snap = led.snapshot()
    assert snap["busy_wall_s"] == pytest.approx(0.25)
    assert snap["attributed_busy_s"] == pytest.approx(0.25)
    (row,) = snap["attributed"]
    assert (row["lane"], row["kernel"], row["chip"]) == ("block", "lane_flush", "0")
    assert row["overlap_s"] == 0.0


def test_nested_dispatch_suppresses_lane_flush_double_count():
    """A lane_flush whose body reached the mesh attributes ONLY the inner
    dispatch (per participating chip, under the flush's lane) — never
    both the flush and the dispatch for the same interval."""
    clock = FakeClock()
    led = DeviceLedger(clock=clock)
    with led.lane_flush("attestation"):
        with led.dispatch("grouped", (0, 1)):
            clock.advance(1.0)
    snap = led.snapshot()
    rows = {(r["lane"], r["kernel"], r["chip"]): r["busy_s"]
            for r in snap["attributed"]}
    assert rows == {
        ("attestation", "grouped", "0"): pytest.approx(1.0),
        ("attestation", "grouped", "1"): pytest.approx(1.0),
    }
    # busy WALL is the union of intervals: 1 s, not 2 chip-seconds
    assert snap["busy_wall_s"] == pytest.approx(1.0)
    assert snap["attributed_busy_s"] == pytest.approx(2.0)
    assert snap["dispatches"] == 1


def test_dispatch_outside_lane_flush_is_unlabeled():
    clock = FakeClock()
    led = DeviceLedger(clock=clock)
    with led.dispatch("bisect", (0,)):
        clock.advance(0.5)
    (row,) = led.snapshot()["attributed"]
    assert row["lane"] == "unlabeled" and row["kernel"] == "bisect"


def test_overlap_hint_accrues_overlap_seconds():
    """The dispatcher's double-buffer hint marks the whole dispatch as
    pipelined against other work — the on-device measure of the
    continuous-batching win."""
    clock = FakeClock()
    led = DeviceLedger(clock=clock)
    with led.lane_flush("attestation", overlapped=True):
        with led.dispatch("grouped", (0,)):
            clock.advance(0.4)
    (row,) = led.snapshot()["attributed"]
    assert row["overlap_s"] == pytest.approx(0.4)
    # idle wall accrues once work stops
    clock.advance(0.6)
    snap = led.snapshot()
    assert snap["idle_wall_s"] == pytest.approx(snap["uptime_s"] - 0.4)
    assert 0.0 < snap["utilization"] < 1.0


def test_pipeline_fanout_exports_device_families():
    p = PipelineMetrics()
    clock = FakeClock()
    led = DeviceLedger(clock=clock)
    led.attach(p)
    with led.lane_flush("block", overlapped=True):
        clock.advance(0.2)
    led.snapshot()
    assert p.device_dispatch_seconds.value(
        lane="block", kernel="lane_flush", chip="0"
    ) == pytest.approx(0.2)
    assert p.device_overlap_seconds.value(
        lane="block", kernel="lane_flush", chip="0"
    ) == pytest.approx(0.2)
    assert p.device_idle_wall.value() >= 0.0
    text = p.registry.expose()
    assert "lodestar_tpu_device_dispatch_seconds_total" in text


def test_real_dispatcher_attributes_95_percent_of_device_wall_time():
    """ISSUE 16 acceptance: drive the REAL BlsLaneDispatcher with a
    sleeping stub verifier — the ledger must attribute >= 95% of the
    wall-clock device time the flushes actually held."""

    class SleepVerifier(MockBlsVerifier):
        def verify_signature_sets(self, sets):
            time.sleep(0.03)
            return super().verify_signature_sets(sets)

    p = PipelineMetrics()
    d = BlsLaneDispatcher(
        SleepVerifier(), max_sigs=32, max_wait_ms=10_000, workers=1,
        pending_cap=0, lane_caps={}, waiter_timeout_s=60.0, pipeline=p,
    )
    try:
        for i in range(4):
            assert d.verify_signature_sets([f"s{i}"], lane="block") is True
    finally:
        d.close()
    snap = device_ledger.ledger().snapshot()
    assert snap["dispatches"] >= 4
    assert snap["busy_wall_s"] >= 4 * 0.03 * 0.9
    assert snap["attributed_busy_s"] >= 0.95 * snap["busy_wall_s"]
    lanes = {r["lane"] for r in snap["attributed"]}
    assert lanes == {"block"}


# --- memory sampler -----------------------------------------------------------


def test_memory_watermark_is_monotonic_and_mem_is_live():
    reads = [
        {"0": {"in_use": 100, "peak": 120, "limit": 1000}},
        {"0": {"in_use": 400, "peak": 400, "limit": 1000}},
        {"0": {"in_use": 50, "peak": 400, "limit": 1000}},
    ]
    p = PipelineMetrics()
    led = DeviceLedger(memory_stats_fn=lambda: reads.pop(0))
    led.attach(p)
    for _ in range(3):
        led.sample_memory(force=True)
    snap = led.snapshot()  # 4th snapshot-sample would pop an empty list,
    assert snap["memory_samples"] == 3  # but the rate limiter holds it
    mem = snap["memory"]["0"]
    assert mem["in_use"] == 50  # live value follows the sampler down
    assert mem["watermark_bytes"] == 400  # watermark never does
    assert p.device_memory.value(chip="0", kind="in_use") == 50
    assert p.device_memory_watermark.value(chip="0") == 400
    # the rises were flight-recorded for the post-mortem
    from lodestar_tpu.observability import flight_recorder
    marks = [e for e in flight_recorder.recorder().dump()["events"]
             if e["kind"] == "device_mem_watermark"]
    assert [m["bytes"] for m in marks[-2:]] == [100, 400]


def test_memory_sampler_disabled_and_erroring_fn_is_contained(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_DEVICE_LEDGER_MEM_SAMPLE_S", "0")
    led = DeviceLedger(memory_stats_fn=lambda: {"0": {"in_use": 9}})
    led.sample_memory()
    assert led.snapshot()["memory_samples"] == 0  # 0 disables
    led.sample_memory(force=True)  # force bypasses the off switch
    assert led.snapshot()["memory"]["0"]["in_use"] == 9

    def boom():
        raise RuntimeError("no allocator stats")

    led2 = DeviceLedger(memory_stats_fn=boom)
    led2.sample_memory(force=True)  # must not raise into the caller
    assert led2.snapshot()["memory_samples"] == 0  # a failed read is no sample
    from lodestar_tpu.observability import flight_recorder
    kinds = [e["kind"] for e in flight_recorder.recorder().dump()["events"]]
    assert "device_mem_sample_error" in kinds


# --- endpoint + post-mortem ---------------------------------------------------


def test_debug_device_endpoint_serves_singleton_snapshot():
    from lodestar_tpu.metrics import MetricsRegistry, MetricsServer

    clock = FakeClock()
    with device_ledger.ledger().dispatch("grouped", (0,)):
        time.sleep(0.01)
    server = MetricsServer(MetricsRegistry(), port=0)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/device"
        with urllib.request.urlopen(url) as r:
            doc = json.load(r)
        assert doc["wired"] is True
        assert doc["dispatches"] == 1
        assert doc["attributed"][0]["kernel"] == "grouped"
    finally:
        server.close()

    server = MetricsServer(MetricsRegistry(), port=0, device=lambda: None)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/device"
        with urllib.request.urlopen(url) as r:
            assert json.load(r) == {"wired": False}
    finally:
        server.close()


def test_watchdog_rc124_emission_carries_device_section():
    """ISSUE 16 acceptance: a timed-out bench round's post-mortem names
    what was on the device — the watchdog document must embed the ledger
    snapshot (sections are read at emit time)."""
    code = (
        "import sys, time\n"
        f"sys.path.insert(0, {REPO_ROOT!r})\n"
        "from lodestar_tpu.observability.bench_emit import BenchEmitter\n"
        "from lodestar_tpu.observability import device_ledger\n"
        "led = device_ledger.ledger()\n"
        "em = BenchEmitter('m', 'sets/s', global_deadline_s=0.3)\n"
        "em.add_section('device', led.snapshot)\n"
        "with led.lane_flush('block'):\n"
        "    time.sleep(0.02)\n"
        "with em.phase('stuck'):\n"
        "    time.sleep(30)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    out, _ = proc.communicate(timeout=20)
    assert proc.returncode == 124
    doc = json.loads(out.strip().splitlines()[-1])
    assert doc["timed_out"] is True
    device = doc["device"]
    assert device["dispatches"] == 1
    assert device["attributed"][0]["lane"] == "block"
    assert device["busy_wall_s"] > 0.0
