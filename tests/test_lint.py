"""Tier-1 enforcement of graftlint (tools/lint) + the generated config docs.

Two halves, per the invariant-checker contract:

* the real tree is CLEAN — `python -m tools.lint` over lodestar_tpu/,
  tools/, bench.py, __graft_entry__.py yields zero findings;
* every rule demonstrably FIRES — each planted-violation fixture in
  tests/lint_fixtures/ produces the expected findings, and the
  rules-fire matrix fails if a checker is deleted or unwired.

Plus suppression semantics, CLI exit codes / JSON output, and the
docs/configuration.md drift check (tools/gen_config_docs.py --check).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURES = os.path.join(os.path.dirname(__file__), "lint_fixtures")
sys.path.insert(0, REPO_ROOT)

from tools.lint import all_checkers, render, rule_names, run  # noqa: E402

EXPECTED_RULES = {
    "trace-safety",
    "lock-discipline",
    "env-registry",
    "exception-hygiene",
    "metric-discipline",
}


def lint_fixture(name: str):
    return run(paths=[os.path.join(FIXTURES, name)], root=REPO_ROOT)


def rules_of(findings):
    return [f.rule for f in findings]


# -- the real tree is clean --------------------------------------------------


def test_repo_tree_has_no_findings():
    findings = run(root=REPO_ROOT)
    assert not findings, "graftlint found violations:\n" + render(findings)


# -- every rule fires on its fixture -----------------------------------------


def test_registered_rule_set():
    assert set(rule_names()) == EXPECTED_RULES
    assert len(all_checkers()) == len(EXPECTED_RULES)


def test_trace_safety_fixture_fires():
    findings = lint_fixture("trace_bad.py")
    assert rules_of(findings) == ["trace-safety"] * 8
    lines = {f.line for f in findings}
    messages = "\n".join(f.message for f in findings)
    assert len(lines) == 8  # one finding per planted site
    for marker in (".item()", "np.asarray", "Python `if`", "float(",
                   ".tolist()", "jax.device_get", ".block_until_ready()",
                   "unhashable list"):
        assert marker in messages


def test_trace_safety_recognizes_pallas_kernels():
    # pl.pallas_call(kernel, …) bodies run under a trace: host syncs
    # survive interpret mode and only explode when Mosaic lowers them,
    # so the checker must treat them as kernels statically (ISSUE 14)
    findings = lint_fixture("pallas_bad.py")
    assert rules_of(findings) == ["trace-safety"] * 3
    assert len({f.line for f in findings}) == 3  # one per planted site
    messages = "\n".join(f.message for f in findings)
    for marker in ("Python `if`", "np.asarray", ".item()"):
        assert marker in messages


def test_lock_discipline_fixture_fires():
    findings = lint_fixture("locks_bad.py")
    assert rules_of(findings) == ["lock-discipline"] * 5
    messages = "\n".join(f.message for f in findings)
    assert messages.count("guarded-by") == 2
    assert "time.sleep" in messages
    assert "untimed .wait()" in messages
    assert ".join()" in messages


def test_env_registry_fixture_fires():
    findings = lint_fixture("env_bad.py")
    assert rules_of(findings) == ["env-registry"] * 4
    messages = "\n".join(f.message for f in findings)
    assert "LODESTAR_TPU_SOME_KNOB" in messages
    assert "LODESTAR_TPU_OTHER_KNOB" in messages
    assert "LODESTAR_TPU_THIRD_KNOB" in messages
    assert "not registered" in messages  # the typo'd accessor name


def test_exception_hygiene_fixture_fires():
    findings = lint_fixture("exceptions_bad.py")
    assert rules_of(findings) == ["exception-hygiene"] * 3
    messages = "\n".join(f.message for f in findings)
    assert "bare `except:`" in messages
    assert "silently swallows" in messages


def test_metric_discipline_fixture_fires():
    findings = lint_fixture("metrics_bad.py")
    assert rules_of(findings) == ["metric-discipline"] * 4
    messages = "\n".join(f.message for f in findings)
    assert "redeclared" in messages
    assert "does not match any declared metric family" in messages
    assert "declaration expects" in messages
    assert "never used" in messages


def test_every_rule_fires_somewhere():
    """The self-test the issue demands: deleting (or unwiring) any checker
    turns this red, because its fixture findings disappear."""
    fired = set()
    for name in os.listdir(FIXTURES):
        if name.endswith(".py"):
            fired.update(rules_of(lint_fixture(name)))
    assert fired == EXPECTED_RULES


# -- suppressions ------------------------------------------------------------


def test_line_suppression(tmp_path):
    src = (
        "def f():\n"
        "    try:\n"
        "        return 1\n"
        "    except Exception:  # graftlint: disable=exception-hygiene\n"
        "        pass\n"
    )
    p = tmp_path / "suppressed.py"
    p.write_text(src)
    assert run(paths=[str(p)], root=REPO_ROOT) == []


def test_line_suppression_is_rule_specific(tmp_path):
    src = (
        "def f():\n"
        "    try:\n"
        "        return 1\n"
        "    except Exception:  # graftlint: disable=trace-safety\n"
        "        pass\n"
    )
    p = tmp_path / "wrong_rule.py"
    p.write_text(src)
    findings = run(paths=[str(p)], root=REPO_ROOT)
    assert rules_of(findings) == ["exception-hygiene"]


def test_file_suppression(tmp_path):
    src = (
        "# graftlint: disable-file=exception-hygiene\n"
        "def f():\n"
        "    try:\n"
        "        return 1\n"
        "    except Exception:\n"
        "        pass\n"
        "def g():\n"
        "    try:\n"
        "        return 2\n"
        "    except Exception:\n"
        "        pass\n"
    )
    p = tmp_path / "filewide.py"
    p.write_text(src)
    assert run(paths=[str(p)], root=REPO_ROOT) == []


def test_suppression_all(tmp_path):
    src = (
        "def f():\n"
        "    try:\n"
        "        return 1\n"
        "    except Exception:  # graftlint: disable=all\n"
        "        pass\n"
    )
    p = tmp_path / "all_off.py"
    p.write_text(src)
    assert run(paths=[str(p)], root=REPO_ROOT) == []


def test_suppression_in_string_literal_does_not_apply(tmp_path):
    src = (
        'MARKER = "graftlint: disable=exception-hygiene"\n'
        "def f():\n"
        "    try:\n"
        "        return MARKER\n"
        "    except Exception:\n"
        "        pass\n"
    )
    p = tmp_path / "string_trap.py"
    p.write_text(src)
    findings = run(paths=[str(p)], root=REPO_ROOT)
    assert rules_of(findings) == ["exception-hygiene"]


# -- CLI ---------------------------------------------------------------------


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.lint", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )


def test_cli_exit_nonzero_on_findings():
    proc = _cli(os.path.join("tests", "lint_fixtures", "exceptions_bad.py"))
    assert proc.returncode == 1
    assert "exception-hygiene" in proc.stdout


def test_cli_exit_zero_on_clean_file():
    proc = _cli(os.path.join("tools", "lint", "__main__.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no findings" in proc.stdout


def test_cli_json_output():
    proc = _cli("--json",
                os.path.join("tests", "lint_fixtures", "env_bad.py"))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["count"] == len(doc["findings"]) == 4
    assert {f["rule"] for f in doc["findings"]} == {"env-registry"}
    assert all(
        set(f) == {"path", "line", "col", "rule", "message"}
        for f in doc["findings"]
    )


def test_cli_rules_subset():
    proc = _cli("--rules", "exception-hygiene",
                os.path.join("tests", "lint_fixtures", "trace_bad.py"))
    assert proc.returncode == 0  # trace violations invisible to that rule
    proc = _cli("--rules", "trace-safety",
                os.path.join("tests", "lint_fixtures", "trace_bad.py"))
    assert proc.returncode == 1


def test_cli_unknown_rule_is_usage_error():
    proc = _cli("--rules", "no-such-rule")
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr


def test_cli_list_rules():
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule in EXPECTED_RULES:
        assert rule in proc.stdout


# -- ruff error-class gate (optional tool, gated) ----------------------------


def test_ruff_error_classes_clean():
    """When ruff is available, the E9/F-only gate configured in
    pyproject [tool.ruff] must pass over the lintable tree. The
    container does not ship ruff — the test skips rather than fails, and
    the F-class true positives were fixed by hand (see the unused-import
    sweep in this PR)."""
    import shutil

    if shutil.which("ruff") is None:
        pytest.skip("ruff not installed in this environment")
    proc = subprocess.run(
        ["ruff", "check", "lodestar_tpu", "tools", "tests", "bench.py"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- generated configuration docs stay fresh ---------------------------------


def test_config_docs_not_stale():
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "gen_config_docs.py"),
         "--check"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        "docs/configuration.md is stale — regenerate with "
        "`python tools/gen_config_docs.py`\n" + proc.stdout + proc.stderr
    )


def test_env_registry_covers_every_knob_reference():
    """No raw LODESTAR_TPU_* read survives outside the typed registry
    (the env-registry rule enforces this for lodestar_tpu/, tools/ and
    bench.py; this asserts the registry itself is importable and
    non-trivial so the rule has teeth)."""
    from lodestar_tpu.utils.env import REGISTRY

    assert len(REGISTRY) >= 25
    assert all(k.startswith("LODESTAR_TPU_") for k in REGISTRY)
    types = {v.type for v in REGISTRY.values()}
    assert types <= {"str", "int", "float", "bool"}
