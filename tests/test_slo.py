"""SLO engine tests (ISSUE 16 tentpole A): budget math per SLI kind,
multi-window burn transitions on scripted histories with a fake clock,
committed-rules round-trip, the /debug/slo endpoint, and the acceptance
case — an injected fault (testing/faults.py) flips an objective from ok
to burning.
"""

import json
import urllib.request

import pytest

from lodestar_tpu.chain.bls_verifier import MockBlsVerifier
from lodestar_tpu.chain.supervisor import SupervisedBlsVerifier
from lodestar_tpu.observability import slo
from lodestar_tpu.observability.slo import SloEngine, load_rules, validate_rules
from lodestar_tpu.observability.stages import PipelineMetrics
from lodestar_tpu.testing import faults

WINDOWS = {"short_s": 300.0, "long_s": 3600.0}


@pytest.fixture(autouse=True)
def _clean():
    slo._reset_for_tests()
    faults.clear(reset_counters=True)
    yield
    slo._reset_for_tests()
    faults.clear(reset_counters=True)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _rules(*objectives):
    return {"windows": dict(WINDOWS), "objectives": list(objectives)}


# --- rules file round-trip ----------------------------------------------------


def test_committed_rules_load_and_evaluate_clean():
    """The committed dashboards/slo_rules.json parses, commits >= 6
    objectives, and every source family resolves against a live
    PipelineMetrics — a fresh node starts with zero objectives burning
    (and zero `absent`: a committed objective over a family this
    registry can't see would never be judged)."""
    rules = load_rules()
    assert len(rules["objectives"]) >= 6
    eng = SloEngine(PipelineMetrics(), rules=rules)
    reports = eng.evaluate()
    assert len(reports) == len(rules["objectives"])
    assert all(r["state"] == "ok" for r in reports)
    assert all(r["runbook"].startswith("docs/observability.md#")
               for r in reports)


def test_validate_rules_rejects_malformed_documents():
    with pytest.raises(ValueError, match="windows"):
        validate_rules({"objectives": [{"name": "x"}]})
    with pytest.raises(ValueError, match="short_s must be <"):
        validate_rules({"windows": {"short_s": 10, "long_s": 10},
                        "objectives": [{}]})
    base = {"windows": dict(WINDOWS)}
    with pytest.raises(ValueError, match="no objectives"):
        validate_rules({**base, "objectives": []})
    with pytest.raises(ValueError, match="unknown kind"):
        validate_rules(_rules(
            {"name": "x", "source": "m", "kind": "percentile_over"}
        ))
    with pytest.raises(ValueError, match="duplicate"):
        validate_rules(_rules(
            {"name": "x", "source": "m", "kind": "counter_zero"},
            {"name": "x", "source": "m", "kind": "counter_zero"},
        ))
    with pytest.raises(ValueError, match="threshold"):
        validate_rules(_rules(
            {"name": "x", "source": "m", "kind": "gauge_under"}
        ))
    with pytest.raises(ValueError, match="good_label"):
        validate_rules(_rules(
            {"name": "x", "source": "m", "kind": "label_ratio"}
        ))


# --- SLI kinds / budget math --------------------------------------------------


def test_counter_zero_burns_on_labeled_bad_event():
    p = PipelineMetrics()
    clock = FakeClock()
    eng = SloEngine(p, rules=_rules({
        "name": "zero_block_sheds",
        "source": "lodestar_bls_lane_shed_total",
        "kind": "counter_zero",
        "labels": {"lane": "block"},
    }), clock=clock)
    clock.advance(1.0)
    # a shed on a DIFFERENT lane is outside the label subset: still ok
    p.lane_shed("attestation", 5)
    (rep,) = eng.evaluate()
    assert rep["state"] == "ok" and rep["bad_events"] == 0
    clock.advance(1.0)
    p.lane_shed("block", 2)
    (rep,) = eng.evaluate()
    assert rep["state"] == "burning"
    assert rep["bad_events"] == 2
    assert rep["budget_remaining"] == 0.0


def test_histogram_under_budget_math():
    """good = observations <= threshold: 9 fast + 1 slow flush against a
    target of 0.95 leaves a 10% bad fraction over a 5% budget — burn
    rate 2.0 in both windows."""
    p = PipelineMetrics()
    clock = FakeClock()
    eng = SloEngine(p, rules=_rules({
        "name": "flush_latency",
        "source": "lodestar_bls_verifier_flush_seconds",
        "kind": "histogram_under",
        "threshold": 0.5,
        "target": 0.95,
    }), clock=clock)
    clock.advance(1.0)
    for _ in range(9):
        p.flush("size", latency_s=0.01)
    p.flush("size", latency_s=2.0)  # over the 0.5 s threshold
    (rep,) = eng.evaluate()
    assert rep["total_events"] == 10 and rep["bad_events"] == 1
    assert rep["burn_rate_short"] == pytest.approx(2.0)
    assert rep["burn_rate_long"] == pytest.approx(2.0)
    assert rep["state"] == "burning"
    assert rep["budget_remaining"] == 0.0
    # 90 more fast flushes dilute the bad fraction to 1% < 5% budget
    clock.advance(1.0)
    for _ in range(90):
        p.flush("size", latency_s=0.01)
    (rep,) = eng.evaluate()
    assert rep["state"] == "ok"
    assert rep["budget_remaining"] == pytest.approx(0.8)


def test_label_ratio_compile_cache_hit_rate():
    p = PipelineMetrics()
    clock = FakeClock()
    eng = SloEngine(p, rules=_rules({
        "name": "compile_cache_hit_rate",
        "source": "lodestar_tpu_compile_events_total",
        "kind": "label_ratio",
        "good_label": {"cache": "hit"},
        "bad_label": {"cache": "miss"},
        "target": 0.9,
    }), clock=clock)
    clock.advance(1.0)
    for _ in range(19):
        p.compile_event("verify_grouped", "hit", 0.001)
    p.compile_event("verify_grouped", "miss", 4.0)
    (rep,) = eng.evaluate()
    assert rep["total_events"] == 20 and rep["bad_events"] == 1
    assert rep["state"] == "ok"  # 5% miss rate inside the 10% budget
    clock.advance(1.0)
    for _ in range(5):
        p.compile_event("verify_bisect", "miss", 4.0)
    (rep,) = eng.evaluate()
    assert rep["state"] == "burning"  # 6/25 = 24% miss vs 10% budget


def test_gauge_under_unset_gauge_contributes_no_sample():
    """A node that never reported serving-ready can't burn the cold-start
    objective; once the gauge reads over threshold, every evaluation is a
    bad sample."""
    p = PipelineMetrics()
    clock = FakeClock()
    eng = SloEngine(p, rules=_rules({
        "name": "serving_ready",
        "source": "lodestar_tpu_serving_ready_seconds",
        "kind": "gauge_under",
        "threshold": 10.0,
        "target": 1.0,
    }), clock=clock)
    clock.advance(1.0)
    (rep,) = eng.evaluate()
    assert rep["state"] == "ok" and rep["total_events"] == 0
    p.serving_ready(22.5)  # blew the 10 s cold-start SLO
    clock.advance(1.0)
    (rep,) = eng.evaluate()
    assert rep["state"] == "burning" and rep["bad_events"] == 1


def test_absent_source_reports_absent_not_crash():
    p = PipelineMetrics()
    eng = SloEngine(p, rules=_rules({
        "name": "phantom",
        "source": "lodestar_not_a_family_total",
        "kind": "counter_zero",
    }))
    (rep,) = eng.evaluate()
    assert rep["state"] == "absent"
    assert rep["budget_remaining"] == 1.0


# --- multi-window burn transitions -------------------------------------------


def test_burn_clears_when_short_window_goes_quiet():
    """Multi-window semantics on a scripted history: a bad burst burns
    (young engine: both windows see it), then once the burst ages past
    the SHORT window the objective recovers even though the long window
    still remembers it — and the recovery is a recorded transition."""
    p = PipelineMetrics()
    clock = FakeClock()
    eng = SloEngine(p, rules=_rules({
        "name": "zero_sheds",
        "source": "lodestar_bls_lane_shed_total",
        "kind": "counter_zero",
    }), clock=clock)
    clock.advance(1.0)
    p.lane_shed("attestation", 1)
    (rep,) = eng.evaluate()
    assert rep["state"] == "burning"
    # quiet evaluations inside the short window: still burning (the bad
    # event is in BOTH trailing windows)
    clock.advance(60.0)
    (rep,) = eng.evaluate()
    assert rep["state"] == "burning"
    # age the burst past the 300 s short window: short goes quiet -> ok
    clock.advance(WINDOWS["short_s"] + 60.0)
    (rep,) = eng.evaluate()
    assert rep["state"] == "ok"
    assert rep["burn_rate_long"] > 0.0  # long window still remembers
    from lodestar_tpu.observability import flight_recorder
    kinds = [e for e in flight_recorder.recorder().dump()["events"]
             if e["kind"] == "slo_transition"]
    states = [e["state"] for e in kinds if e["objective"] == "zero_sheds"]
    assert states[-2:] == ["burning", "ok"]


def test_slo_families_exported_on_pipeline():
    p = PipelineMetrics()
    clock = FakeClock()
    eng = SloEngine(p, rules=_rules({
        "name": "zero_sheds",
        "source": "lodestar_bls_lane_shed_total",
        "kind": "counter_zero",
    }), clock=clock)
    clock.advance(1.0)
    p.lane_shed("block", 1)
    eng.evaluate()
    assert p.slo_burning.value(objective="zero_sheds") == 1
    assert p.slo_budget_remaining.value(objective="zero_sheds") == 0.0
    assert p.slo_burn_rate.value(objective="zero_sheds", window="short") > 0
    assert p.slo_evaluations.value() >= 2  # baseline + explicit
    text = p.registry.expose()
    assert "lodestar_slo_burning" in text


# --- singleton / poke / endpoint ---------------------------------------------


def test_install_engine_snapshot_and_poke_rate_limit(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_SLO_POKE_S", "3600")
    assert slo.snapshot_or_none() is None  # nothing installed yet
    p = PipelineMetrics()
    eng = slo.install(p)
    assert slo.engine() is eng
    snap = slo.snapshot_or_none()
    assert snap["rules_path"].endswith("slo_rules.json")
    assert snap["burning"] == []
    assert {o["name"] for o in snap["objectives"]} == set(eng.objectives())
    before = snap["evaluations"]
    slo.poke()  # first poke evaluates
    slo.poke()  # rate-limited: swallowed
    with eng._lock:
        evals = eng._evaluations
    assert evals == before + 1


def test_debug_slo_endpoint_serves_engine_snapshot():
    from lodestar_tpu.metrics import MetricsRegistry, MetricsServer

    server = MetricsServer(MetricsRegistry(), port=0)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/slo"
        with urllib.request.urlopen(url) as r:
            assert json.load(r) == {"wired": False}  # no engine installed
        p = PipelineMetrics()
        slo.install(p)
        p.lane_shed("block", 1)
        with urllib.request.urlopen(url) as r:
            doc = json.load(r)
        assert doc["wired"] is True
        assert "zero_block_sheds" in doc["burning"]
        by_name = {o["name"]: o for o in doc["objectives"]}
        assert by_name["zero_block_sheds"]["state"] == "burning"
        assert by_name["zero_block_sheds"]["runbook"]
    finally:
        server.close()


# --- ISSUE 16 acceptance: injected fault flips an objective ------------------


class _FaultyDevice:
    """Device verifier that routes through the testing/faults seam, like
    TpuBlsVerifier does on every dispatch."""

    observer = None

    def verify_signature_sets(self, sets):
        faults.on_device_dispatch(len(sets))
        return True

    def verify_signature_sets_individual(self, sets):
        faults.on_device_dispatch(len(sets))
        return [True] * len(sets)


def test_injected_fault_flips_breaker_objective_to_burning():
    """testing/faults exception mode opens the supervisor breaker; the
    committed `breaker_closed` objective must go ok -> burning on the
    next evaluation (the alert an operator would page on)."""
    p = PipelineMetrics()
    sup = SupervisedBlsVerifier(
        _FaultyDevice(), MockBlsVerifier(), observer=p,
        deadline_s=5.0, failure_threshold=2, retries=0,
        retry_base_delay_s=0.001, canary_thread=False,
        canary_sets=[object()],
    )
    eng = slo.install(p)
    by_name = {r["name"]: r for r in eng.evaluate()}
    assert by_name["breaker_closed"]["state"] == "ok"
    faults.configure("exception")
    sup.verify_signature_sets([object()])
    sup.verify_signature_sets([object()])
    assert p.supervisor_breaker_state.value() == 2  # breaker open
    by_name = {r["name"]: r for r in eng.evaluate()}
    assert by_name["breaker_closed"]["state"] == "burning"
    assert p.slo_burning.value(objective="breaker_closed") == 1
