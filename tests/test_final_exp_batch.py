"""Differential suite for the shared-inversion batched final
exponentiation (`pairing.final_exponentiation_batch`, ISSUE 14).

Every verdict path — per-set, grouped, pk-grouped, split, bisect root,
bisect probe, and the sharded twins — now routes its final exps through
this one entry, so it must be bit-identical to per-lane
`final_exponentiation` on random inputs AND on the edges the Montgomery
product trick is worst at: the identity lane and the non-invertible
all-zero lane (a single zero would otherwise poison the whole batch's
shared inversion; the kernel substitutes the identity and forces that
lane's inverse back to zero, reproducing per-lane `inv(0) = 0^(p-2) = 0`
exactly).

The routing assertion is fast tier (pure source scan); the numeric
differential compiles two deep final-exp kernels (~1-2 min each on CPU)
and lives in the slow tier with the rest of the deep-kernel compiles.
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.ops import fp, fp12
from lodestar_tpu.ops import pairing as dp

RNG = np.random.default_rng(909)


def test_all_verdict_paths_route_batched_fe():
    """No verdict path may call per-lane `final_exponentiation` directly:
    the only surviving call site is the bench comparison baseline
    (`individual_verify_kernel_legacy_fe`)."""
    import inspect

    from lodestar_tpu.parallel import sharded, verifier

    bare_call = re.compile(r"\bfinal_exponentiation\(")
    v_calls = bare_call.findall(inspect.getsource(verifier))
    s_calls = bare_call.findall(inspect.getsource(sharded))
    assert len(v_calls) == 1, (
        "verifier.py may call per-lane final_exponentiation exactly once "
        "(the legacy-FE bench baseline); found %d call sites" % len(v_calls)
    )
    assert not s_calls, "sharded.py must route final_exponentiation_one/_batch"
    legacy_src = inspect.getsource(verifier.individual_verify_kernel_legacy_fe)
    assert bare_call.search(legacy_src), (
        "the one bare call site must be the legacy-FE bench baseline"
    )


@pytest.mark.slow
def test_batched_matches_per_lane_on_random_and_edge_lanes():
    lanes = [
        jnp.asarray(
            RNG.integers(0, 1 << 12, size=(2, 3, 2, 32), dtype=np.int32)
        )
        for _ in range(2)
    ]
    lanes.append(fp12.one(()))   # identity lane
    lanes.append(fp12.zero(()))  # non-invertible lane (fallback path)
    fs = jnp.stack(lanes)

    per = jax.jit(dp.final_exponentiation)(fs)
    bat = jax.jit(dp.final_exponentiation_batch)(fs)
    # bit-identical AFTER canonicalization: the two tails may differ in
    # which Montgomery representative they leave, but the verdict
    # comparisons (`fp12.is_one`/`eq`) canonicalize — and in practice the
    # smoke runs came out raw-identical too
    assert bool(jnp.all(fp.canonical(bat) == fp.canonical(per)))
    # the zero lane must map to zero (per-lane Fermat: 0^(p-2) = 0),
    # never poison its neighbors
    assert bool(jnp.all(fp.canonical(bat[-1]) == 0))
    assert bool(jnp.all(fp.canonical(bat[:-1]) == fp.canonical(per[:-1])))
    # the n = 1 wrapper every single-product verdict path uses
    one = jax.jit(dp.final_exponentiation_one)(fs[0])
    assert bool(jnp.all(fp.canonical(one) == fp.canonical(per[0])))
