"""Backfill sync: checkpoint-anchored reverse history sync with linkage +
batched proposer-signature verification (reference: sync/backfill e2e)."""

import pytest

from lodestar_tpu.chain import CpuBlsVerifier
from lodestar_tpu.db import BeaconDb
from lodestar_tpu.network.reqresp.handlers import ReqRespHandlers
from lodestar_tpu.sync import LocalPeer
from lodestar_tpu.sync.backfill import BackfillError, BackfillSync
from lodestar_tpu.params.presets import MINIMAL
from tests.test_sync import two_nodes  # noqa: F401  (fixture reuse)

SPE = MINIMAL.SLOTS_PER_EPOCH


def test_backfill_to_genesis(two_nodes):  # noqa: F811
    config, types, node_a, _ = two_nodes
    # anchor: node A's head block + state (checkpoint-sync style)
    anchor_root = node_a.head_root
    anchor_block = node_a.blocks[anchor_root]
    anchor_state = node_a.head_state.state

    db = BeaconDb(types)
    bf = BackfillSync(
        config, types, db, anchor_block, anchor_state, CpuBlsVerifier()
    )
    bf.add_peer(LocalPeer("nodeA", ReqRespHandlers(config, types, node_a), types))
    archived = bf.sync_to_genesis()
    # everything below the head is archived and linked
    assert archived == 2 * SPE - 1
    slots = [b.message.slot for b in db.block_archive.values_stream()]
    assert slots == list(range(1, 2 * SPE))


def test_backfill_rejects_tampered_history(two_nodes):  # noqa: F811
    config, types, node_a, _ = two_nodes

    class TamperingPeer(LocalPeer):
        def beacon_blocks_by_range(self, start_slot, count):
            blocks = super().beacon_blocks_by_range(start_slot, count)
            if blocks:
                # resign-free tamper: flip the proposer signature
                blocks[0].signature = b"\x13" * 96
            return blocks

    anchor_root = node_a.head_root
    db = BeaconDb(types)
    bf = BackfillSync(
        config, types, db, node_a.blocks[anchor_root],
        node_a.head_state.state, CpuBlsVerifier(),
    )
    bf.add_peer(
        TamperingPeer("evil", ReqRespHandlers(config, types, node_a), types)
    )
    with pytest.raises(BackfillError):
        bf.sync_to_genesis()
