"""Differential tests: device curve ops (ops/points.py) vs CPU oracle.

Every device result is converted back through io_host and compared to the
big-int oracle — the same strategy the reference uses for blst vs herumi
(both must agree on spec vectors)."""

import numpy as np
import pytest

from lodestar_tpu.bls import curve as oc
from lodestar_tpu.bls.fields import R as CURVE_ORDER
from lodestar_tpu.ops import points
from lodestar_tpu.ops.io_host import (
    g1_affine_to_limbs,
    g2_affine_to_limbs,
    limbs_to_fq,
    limbs_to_fq2,
    scalar_to_bits,
)

RNG = np.random.default_rng(1234)


def _rand_scalar():
    return int(RNG.integers(1, 2**62)) % CURVE_ORDER


def _rand_g1():
    return oc.PointG1.generator() * _rand_scalar()


def _rand_g2():
    return oc.PointG2.generator() * _rand_scalar()


def _g1_dev(p):
    x, y, _ = g1_affine_to_limbs(p)
    return points.g1.from_affine(np.asarray(x), np.asarray(y))


def _g2_dev(p):
    x, y, _ = g2_affine_to_limbs(p)
    return points.g2.from_affine(np.asarray(x), np.asarray(y))


def _g1_back(dev_point):
    x, y = points.g1.to_affine(dev_point)
    from lodestar_tpu.bls.fields import Fq

    return oc.PointG1(limbs_to_fq(np.asarray(x)), limbs_to_fq(np.asarray(y)), Fq.one())


def _g2_back(dev_point):
    x, y = points.g2.to_affine(dev_point)
    from lodestar_tpu.bls.fields import Fq2

    return oc.PointG2(
        limbs_to_fq2(np.asarray(x)), limbs_to_fq2(np.asarray(y)), Fq2.one()
    )


class TestG1:
    def test_add(self):
        p, q = _rand_g1(), _rand_g1()
        got = _g1_back(points.g1.add(_g1_dev(p), _g1_dev(q)))
        assert got == p + q

    def test_double(self):
        p = _rand_g1()
        assert _g1_back(points.g1.double(_g1_dev(p))) == p.double()

    def test_add_mixed(self):
        p, q = _rand_g1(), _rand_g1()
        x, y, _ = g1_affine_to_limbs(q)
        got = _g1_back(points.g1.add_mixed(_g1_dev(p), (np.asarray(x), np.asarray(y))))
        assert got == p + q

    def test_add_inverse_gives_infinity(self):
        p = _rand_g1()
        dev = points.g1.add(_g1_dev(p), points.g1.neg(_g1_dev(p)))
        assert bool(points.g1.is_infinity(dev))

    def test_add_equal_points_matches_double(self):
        # Complete formulas: P + P must equal double(P), no special-casing.
        p = _rand_g1()
        assert _g1_back(points.g1.add(_g1_dev(p), _g1_dev(p))) == p.double()

    def test_scalar_mul(self):
        p = _rand_g1()
        k = int(RNG.integers(1, 2**63))
        x, y, _ = g1_affine_to_limbs(p)
        bits = scalar_to_bits(k, 64)
        got = _g1_back(
            points.g1.scalar_mul_bits(bits, (np.asarray(x), np.asarray(y)))
        )
        assert got == p * k

    def test_scalar_mul_batched(self):
        ps = [_rand_g1() for _ in range(4)]
        ks = [int(RNG.integers(1, 2**63)) for _ in range(4)]
        xs = np.stack([g1_affine_to_limbs(p)[0] for p in ps])
        ys = np.stack([g1_affine_to_limbs(p)[1] for p in ps])
        bits = np.stack([scalar_to_bits(k, 64) for k in ks])
        out = points.g1.scalar_mul_bits(bits, (xs, ys))
        for i in range(4):
            got = _g1_back((out[0][i], out[1][i], out[2][i]))
            assert got == ps[i] * ks[i]


class TestG2:
    def test_add(self):
        p, q = _rand_g2(), _rand_g2()
        assert _g2_back(points.g2.add(_g2_dev(p), _g2_dev(q))) == p + q

    def test_double(self):
        p = _rand_g2()
        assert _g2_back(points.g2.double(_g2_dev(p))) == p.double()

    def test_scalar_mul(self):
        p = _rand_g2()
        k = int(RNG.integers(1, 2**63))
        x, y, _ = g2_affine_to_limbs(p)
        bits = scalar_to_bits(k, 64)
        got = _g2_back(
            points.g2.scalar_mul_bits(bits, (np.asarray(x), np.asarray(y)))
        )
        assert got == p * k

    def test_eq_infinity(self):
        inf = points.g2.infinity()
        assert bool(points.g2.eq(inf, inf))
        assert bool(points.g2.is_infinity(inf))


def test_generator_constants_roundtrip():
    gen = oc.PointG1.generator()
    got = _g1_back(points.g1.from_affine(points.G1_GEN_X, points.G1_GEN_Y))
    assert got == gen
    gen2 = oc.PointG2.generator()
    got2 = _g2_back(points.g2.from_affine(points.G2_GEN_X, points.G2_GEN_Y))
    assert got2 == gen2


@pytest.mark.slow
def test_scalar_mul_windowed_matches_bit_ladder():
    """The windowed ladder (verifier default) must agree with the bit
    ladder and the oracle for random 64-bit scalars, on both curves."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from lodestar_tpu.bls import curve as oc
    from lodestar_tpu.ops.io_host import g1_affine_to_limbs, g2_affine_to_limbs
    from lodestar_tpu.ops.points import g1, g2

    rng = np.random.default_rng(5)
    scalars = [int(x) for x in rng.integers(1, 1 << 64, 3, dtype=np.uint64)]
    bits = np.zeros((3, 64), np.int32)
    for i, k in enumerate(scalars):
        for j in range(64):
            bits[i, j] = (k >> (63 - j)) & 1

    for curve, gen, to_limbs in (
        (g1, oc.PointG1.generator(), g1_affine_to_limbs),
        (g2, oc.PointG2.generator(), g2_affine_to_limbs),
    ):
        gx, gy, _ = to_limbs(gen)
        p_bits = jax.jit(curve.scalar_mul_bits)(jnp.asarray(bits), (gx, gy))
        p_win = jax.jit(curve.scalar_mul_windowed)(jnp.asarray(bits), (gx, gy))
        for i, k in enumerate(scalars):
            wx, wy, _ = to_limbs(gen * k)
            got = curve.to_affine(tuple(c[i] for c in p_win))
            assert np.array_equal(np.asarray(got[0]), np.asarray(wx)), k
            assert np.array_equal(np.asarray(got[1]), np.asarray(wy)), k
            gb = curve.to_affine(tuple(c[i] for c in p_bits))
            assert np.array_equal(np.asarray(got[0]), np.asarray(gb[0]))
