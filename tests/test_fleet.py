"""Fleet-serving policy tests (ISSUE 20): two-level dispatcher state
machine, subnet router, host fault injection, and the supervisor's
host-eviction ladder.

Everything here is HOST-side policy — fleet layout sizing, host
eviction/re-admission, verifier-cache keying by host set, rendezvous
subnet routing, the exhaust-to-CPU-oracle ladder — driven with stub
verifier factories and fake devices so no kernel ever compiles (the
two-level collective math itself is proven by tools/dryrun_fleet.py and
the slow sharded tier)."""

import pytest

from lodestar_tpu.chain.supervisor import SupervisedBlsVerifier
from lodestar_tpu.observability.stages import PipelineMetrics
from lodestar_tpu.parallel.fleet import FleetRouter, FleetTopology
from lodestar_tpu.parallel.mesh import BlsMeshDispatcher
from lodestar_tpu.testing import faults
from lodestar_tpu.testing.faults import InjectedHostFault

SUBNETS = 64


class _FakeGrouped:
    class _Arr:
        def __init__(self, shape):
            self.shape = shape

    def __init__(self, rows, lanes):
        self.pk_x = self._Arr((rows, lanes))
        self.msg_x = self._Arr((rows, lanes))


class _StubVerifier:
    def __init__(self, kind, devices, axis):
        self.kind = kind
        self.devices = devices
        self.axis = axis
        self.submits = 0

    def submit(self, *args):
        self.submits += 1
        return True


def _factory_recorder(calls):
    def factory(kind, devices, axis):
        v = _StubVerifier(kind, devices, axis)
        calls.append(v)
        return v

    return factory


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear(reset_counters=True)
    yield
    faults.clear(reset_counters=True)


def _fleet_dispatcher(host_widths=(4, 4), observer=None, calls=None,
                      router=None):
    calls = calls if calls is not None else []
    devices, hosts, i = [], [], 0
    for w in host_widths:
        hosts.append(list(range(i, i + w)))
        devices.extend(f"dev{j}" for j in range(i, i + w))
        i += w
    return BlsMeshDispatcher(
        devices,
        observer=observer or PipelineMetrics(),
        verifier_factory=_factory_recorder(calls),
        hosts=hosts,
        router=router,
    )


# -- FleetRouter: rendezvous subnet routing --------------------------------


def test_router_deterministic_disjoint_covering():
    r0 = FleetRouter(4, 0)
    r1 = FleetRouter(4, 1)
    # same host census => identical owner map on every rank
    assert [r0.owner(s) for s in range(SUBNETS)] == [
        r1.owner(s) for s in range(SUBNETS)
    ]
    slices = [r0.slice_for(h) for h in range(4)]
    seen = [s for sl in slices for s in sl]
    assert sorted(seen) == list(range(SUBNETS))  # covering + disjoint
    assert all(len(sl) > 0 for sl in slices)  # no starved host
    for h, sl in enumerate(slices):
        assert all(r0.owner(s) == h and FleetRouter(4, h).owns(s)
                   for s in sl)


def test_router_eviction_moves_only_the_dead_hosts_subnets():
    r = FleetRouter(4, 0)
    before = {s: r.owner(s) for s in range(SUBNETS)}
    dead = r.slice_for(2)
    moved = r.evict_host(2)
    assert moved == len(dead)
    after = {s: r.owner(s) for s in range(SUBNETS)}
    # rendezvous hashing: survivors keep every subnet they already owned
    for s in range(SUBNETS):
        if before[s] != 2:
            assert after[s] == before[s]
        else:
            assert after[s] != 2
    # re-admission restores the exact original map
    assert r.readmit_hosts() == 1
    assert {s: r.owner(s) for s in range(SUBNETS)} == before


def test_router_eviction_edge_cases_and_snapshot():
    r = FleetRouter(2, 1)
    assert r.evict_host(7) is None  # unknown host: no-op
    assert r.evict_host(0) is not None
    assert r.evict_host(1) is None  # last serving host stays
    r.record_foreign(3)
    snap = r.snapshot()
    assert snap["hosts"] == 2 and snap["rank"] == 1
    assert snap["active_hosts"] == [1] and snap["evicted_hosts"] == [0]
    assert snap["owned"] == SUBNETS
    assert list(snap["owned_subnets"]) == list(range(SUBNETS))
    assert snap["rebalances"] == 1 and snap["foreign_dropped"] == 1
    assert snap["subnets_moved"] > 0
    assert r.readmit_hosts() == 1 and r.snapshot()["evicted_hosts"] == []


def test_router_rebalance_notifies_observer():
    obs = PipelineMetrics()
    r = FleetRouter(2, 0, observer=obs)
    r.evict_host(1)
    snap = obs.fleet_snapshot()
    assert snap["rebalances"] == 1
    assert snap["subnets_moved"] == len(FleetRouter(2, 1).slice_for(1))


# -- FleetTopology: env parsing + device grouping --------------------------


def test_topology_env_parsing(monkeypatch):
    monkeypatch.delenv("LODESTAR_TPU_FLEET", raising=False)
    assert FleetTopology.from_env().mode == "off"
    monkeypatch.setenv("LODESTAR_TPU_FLEET", "emulate")
    monkeypatch.setenv("LODESTAR_TPU_FLEET_HOSTS", "2")
    topo = FleetTopology.from_env()
    assert topo.mode == "emulate" and topo.active and topo.hosts == 2
    monkeypatch.setenv("LODESTAR_TPU_FLEET", "coord-host:9777")
    topo = FleetTopology.from_env()
    assert topo.mode == "distributed"
    assert topo.coordinator == "coord-host:9777"
    # nonsense rank must degrade to off, never raise at node startup
    monkeypatch.setenv("LODESTAR_TPU_FLEET_RANK", "5")
    assert FleetTopology.from_env().mode == "off"


def test_topology_emulate_groups_devices_contiguously():
    topo = FleetTopology(mode="emulate", hosts=2, rank=0)
    rows = topo.group_devices([f"d{i}" for i in range(8)])
    assert rows == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert topo.group_devices(["d0"]) is None  # nothing to split
    off = FleetTopology(mode="off")
    assert off.group_devices([f"d{i}" for i in range(8)]) is None


# -- two-level dispatcher: layout, cache, census ---------------------------


def test_fleet_dispatch_routes_two_level_and_counts():
    calls = []
    obs = PipelineMetrics()
    d = _fleet_dispatcher((4, 4), observer=obs, calls=calls)
    assert d.size == 8 and d.hosts_serving == 2 and d.hosts_total == 2
    g = _FakeGrouped(8, 64)
    assert d.dispatch_grouped(g, None, None) is True
    assert len(calls) == 1
    # the factory saw per-host ROWS and the (dcn, ici) axis pair
    assert calls[0].devices == [
        ["dev0", "dev1", "dev2", "dev3"], ["dev4", "dev5", "dev6", "dev7"]
    ]
    assert calls[0].axis == (d.dcn_axis, d.ici_axis)
    assert d.dispatch_grouped(g, None, None) is True
    assert len(calls) == 1 and calls[0].submits == 2  # cached
    snap = d.fleet_snapshot()
    assert snap["hosts_serving"] == 2
    assert snap["host_dispatches"] == {"0": 2, "1": 2}
    assert obs.fleet_snapshot()["host_dispatches"] == {"0": 2, "1": 2}


def test_fleet_layout_uniform_pow2_rows():
    # ragged host widths: every row is trimmed to the SAME pow2 width
    # (min across hosts) so the (hosts, chips) device grid is rectangular
    calls = []
    d = _fleet_dispatcher((4, 3), calls=calls)
    assert d.size == 4  # 2 hosts x 2 chips
    g = _FakeGrouped(8, 64)
    assert d.dispatch_grouped(g, None, None) is True
    assert calls[0].devices == [["dev0", "dev1"], ["dev4", "dev5"]]


def test_fleet_verifier_cache_keyed_by_host_set():
    calls = []
    d = _fleet_dispatcher((2, 2), calls=calls)
    g = _FakeGrouped(8, 64)
    assert d.dispatch_grouped(g, None, None) is True
    assert d.evict_host(1, reason="drill") is not None
    assert d.dispatch_grouped(g, None, None) is True
    d.readmit()
    assert d.dispatch_grouped(g, None, None) is True
    # two distinct host sets -> two compiles; the readmitted layout
    # reuses the first verifier (cache hit, no third compile)
    assert len(calls) == 2
    assert calls[0].devices == [["dev0", "dev1"], ["dev2", "dev3"]]
    assert calls[1].devices == ["dev0", "dev1"]  # single-host: flat
    assert calls[1].axis == "dp"
    assert calls[0].submits == 2 and calls[1].submits == 1


def test_host_eviction_rebalances_and_readmit_restores():
    obs = PipelineMetrics()
    router = FleetRouter(2, 0, observer=obs)
    d = _fleet_dispatcher((4, 4), observer=obs, router=router)
    moved_expected = len(router.slice_for(1))
    assert d.evict_host(1, reason="drill") == 4
    assert d.hosts_serving == 1 and d.has_evicted()
    assert router.snapshot()["active_hosts"] == [0]
    snap = d.fleet_snapshot()
    assert snap["evicted_hosts"] == [{"host": 1, "reason": "drill"}]
    assert snap["router"]["subnets_moved"] == moved_expected
    counters = obs.fleet_snapshot()
    assert counters["host_evictions"] == {"drill": 1}
    assert counters["subnets_moved"] == moved_expected
    # readmission restores the full fleet AND the router census
    assert d.readmit() == 1
    assert d.hosts_serving == 2 and not d.has_evicted()
    assert router.snapshot()["evicted_hosts"] == []


def test_host_eviction_edge_cases():
    d = _fleet_dispatcher((4, 4))
    assert d.evict_host(1) == 4
    assert d.evict_host() is None  # last serving host stays
    single = BlsMeshDispatcher(
        [f"dev{i}" for i in range(4)],
        observer=PipelineMetrics(),
        verifier_factory=_factory_recorder([]),
    )
    assert single.evict_host() is None  # single-host census: no-op
    assert single.fleet_snapshot() is None  # /debug/fleet -> wired: false


def test_unattributed_host_eviction_keeps_root_host():
    # host 0 owns the two-level root tail: default eviction must drop
    # the highest-rank active host, never host 0
    d = _fleet_dispatcher((2, 2, 2, 2))
    assert d.hosts_serving == 4
    d.evict_host()
    d.evict_host()
    snap = d.fleet_snapshot()
    assert [e["host"] for e in snap["evicted_hosts"]] == [3, 2]
    assert d.hosts_serving == 2


def test_host_fault_is_one_shot_and_attributed():
    faults.configure("host:1")
    d = _fleet_dispatcher((2, 2))
    g = _FakeGrouped(8, 64)
    with pytest.raises(InjectedHostFault) as exc:
        d.dispatch_grouped(g, None, None)
    assert exc.value.host == 1
    # one-shot: the plan disarmed itself, the next dispatch serves
    assert d.dispatch_grouped(g, None, None) is True
    assert faults.snapshot()["injected"]["host"] == 1


# -- supervisor: host-eviction ladder --------------------------------------


class _FakeFleetDevice:
    """Device facade over a 2x2 fleet dispatcher whose scripted failures
    raise attributed host faults; mirrors the mesh_* surface the
    supervisor duck-types (verifier.py passthroughs)."""

    def __init__(self, fail_hosts=(1,), router=None):
        self._pending = list(fail_hosts)
        self.dispatcher = _fleet_dispatcher((2, 2), router=router)
        self.calls = 0

    def verify_signature_sets(self, sets):
        self.calls += 1
        if self._pending:
            raise InjectedHostFault(self._pending.pop(0))
        return True

    def mesh_evict(self, chip=None, reason="failure"):
        return self.dispatcher.evict(chip=chip, reason=reason)

    def mesh_evict_host(self, host=None, reason="failure"):
        return self.dispatcher.evict_host(host=host, reason=reason)

    def mesh_readmit(self):
        return self.dispatcher.readmit()

    def mesh_has_evicted(self):
        return self.dispatcher.has_evicted()

    def mesh_snapshot(self):
        return self.dispatcher.snapshot()

    def fleet_snapshot(self):
        return self.dispatcher.fleet_snapshot()


class _FakeCpu:
    def __init__(self):
        self.calls = 0

    def verify_signature_sets(self, sets):
        self.calls += 1
        return True

    def verify_signature_sets_individual(self, sets):
        self.calls += 1
        return [True] * len(sets)


def _supervised(device, **kw):
    return SupervisedBlsVerifier(
        device,
        _FakeCpu(),
        observer=PipelineMetrics(),
        deadline_s=0,
        canary_thread=False,
        **kw,
    )


def test_supervisor_evicts_sick_host_and_keeps_serving():
    router = FleetRouter(2, 0)
    device = _FakeFleetDevice(fail_hosts=(1,), router=router)
    sup = _supervised(device)
    assert sup.verify_signature_sets([object()]) is True
    # the host fault cost one eviction + immediate retry: no CPU
    # fallback, no transient retry, no breaker feed
    assert device.calls == 2
    assert sup.cpu.calls == 0
    assert sup.breaker_state == "closed"
    assert sup._consecutive_failures == 0
    snap = device.fleet_snapshot()
    assert snap["evicted_hosts"] == [
        {"host": 1, "reason": "InjectedHostFault"}
    ]
    assert snap["hosts_serving"] == 1
    # the drill's other half: the router rebalanced the dead host's slice
    assert snap["router"]["active_hosts"] == [0]
    assert snap["router"]["subnets_moved"] > 0


def test_supervisor_host_eviction_does_not_burn_retry_budget():
    # host fault then chip fault: two eviction retries, more than the
    # 1-retry transient budget — all absorbed without the CPU oracle
    from lodestar_tpu.testing.faults import InjectedChipFault

    device = _FakeFleetDevice(fail_hosts=(1,))
    device._pending = [InjectedHostFault(1), InjectedChipFault(0)]

    def scripted(sets):
        device.calls += 1
        if device._pending:
            raise device._pending.pop(0)
        return True

    device.verify_signature_sets = scripted
    sup = _supervised(device)
    assert sup.verify_signature_sets([object()]) is True
    assert device.calls == 3
    assert sup.cpu.calls == 0


def test_supervisor_falls_back_once_fleet_exhausted():
    # every dispatch raises host faults: the first eviction drops host 1,
    # then (host 0 unevictable — last one serving) the CHIP ladder
    # absorbs what it can, and only once both tiers are exhausted does
    # the ordinary failure policy take over (retry, then CPU oracle)
    device = _FakeFleetDevice(fail_hosts=(1, 0, 0, 0, 0, 0))
    sup = _supervised(device)
    assert sup.verify_signature_sets([object()]) is True
    assert sup.cpu.calls == 1
    assert device.dispatcher.hosts_serving == 1


def test_supervisor_probe_readmits_evicted_hosts():
    device = _FakeFleetDevice(fail_hosts=(1,))
    sup = _supervised(device)
    assert sup.verify_signature_sets([object()]) is True
    assert device.mesh_has_evicted()
    sup._canary_sets = [object()]
    assert sup.probe() is True
    assert not device.mesh_has_evicted()
    assert device.dispatcher.hosts_serving == 2
