"""Req/Resp tests: snappy framing, wire codec, protocol handlers end-to-end
(reference: reqresp encodingStrategies unit tests + handler e2e)."""

import os

import pytest

from lodestar_tpu.network.reqresp import (
    PROTOCOLS,
    Protocol,
    RespCode,
    decode_request,
    decode_response_chunks,
    encode_request,
    encode_response_chunk,
    encode_error_chunk,
    protocol_id,
)
from lodestar_tpu.network.reqresp.protocols import parse_protocol_id
from lodestar_tpu.network.reqresp.snappy_frames import (
    compress_frames,
    crc32c,
    decompress_frames,
)


def test_crc32c_vectors():
    # RFC 3720 / known CRC32C vectors
    assert crc32c(b"") == 0x00000000
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"\x00" * 32) == 0x8A9136AA


def test_snappy_framing_roundtrip():
    for data in (b"", b"x", b"hello " * 1000, os.urandom(200_000)):
        framed = compress_frames(data)
        assert decompress_frames(framed) == data
    with pytest.raises(ValueError):
        decompress_frames(b"not a stream")
    framed = bytearray(compress_frames(b"payload payload payload"))
    framed[-1] ^= 0xFF
    with pytest.raises(ValueError):
        decompress_frames(bytes(framed))


def test_request_codec_roundtrip():
    payload = os.urandom(500)
    wire = encode_request(payload)
    assert decode_request(wire) == payload


def test_response_chunks_roundtrip():
    chunks = [os.urandom(100), b"", os.urandom(70000)]
    wire = b"".join(encode_response_chunk(c) for c in chunks)
    wire += encode_error_chunk(RespCode.RESOURCE_UNAVAILABLE, "pruned")
    decoded = decode_response_chunks(wire)
    assert [c for _, c in decoded[:3]] == chunks
    assert all(code == RespCode.SUCCESS for code, _ in decoded[:3])
    assert decoded[3][0] == RespCode.RESOURCE_UNAVAILABLE
    assert decoded[3][1] == b"pruned"


def test_protocol_ids():
    pid = protocol_id(Protocol.BeaconBlocksByRange, 2)
    assert pid == "/eth2/beacon_chain/req/beacon_blocks_by_range/2/ssz_snappy"
    assert parse_protocol_id(pid) == (Protocol.BeaconBlocksByRange, 2)
    assert len(PROTOCOLS) == 10


def test_handlers_against_live_chain(tmp_path):
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.network.reqresp.handlers import ReqRespHandlers
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.state_transition import interop_genesis_state
    from lodestar_tpu.types import get_types
    from tests.test_chain import _sign_block, _sk
    from lodestar_tpu.state_transition.block import _epoch_signing_root
    from lodestar_tpu.params import DOMAIN_RANDAO
    from lodestar_tpu.state_transition import process_slots

    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, 16, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    chain = BeaconChain(config, types, state)
    blocks = []
    for slot in range(1, 5):
        chain.clock.set_slot(slot)
        trial = chain.head_state.copy()
        if slot > trial.state.slot:
            process_slots(trial, types, slot)
        proposer = trial.epoch_ctx.get_beacon_proposer(slot)
        reveal = _sk(proposer).sign(
            _epoch_signing_root(0, config.get_domain(DOMAIN_RANDAO, slot))
        ).to_bytes()
        block = chain.produce_block(slot, randao_reveal=reveal)
        signed = _sign_block(config, types, block)
        chain.process_block(signed, verify_signatures=False)
        blocks.append(signed)

    handlers = ReqRespHandlers(config, types, chain)

    # status reflects head
    status_wire = handlers.on_status(None)
    (code, payload), = decode_response_chunks(status_wire)
    assert code == RespCode.SUCCESS
    status = types.Status.deserialize(payload)
    assert status.head_slot == 4
    assert bytes(status.head_root) == chain.head_root

    # by-range returns the produced blocks in slot order
    wire = handlers.on_beacon_blocks_by_range(1, 10)
    chunks = decode_response_chunks(wire)
    got = [types.SignedBeaconBlock.deserialize(p).message.slot for _, p in chunks]
    assert got == [1, 2, 3, 4]

    # by-root finds a specific block
    root = blocks[2].message.hash_tree_root()
    wire2 = handlers.on_beacon_blocks_by_root([root, b"\x00" * 32])
    chunks2 = decode_response_chunks(wire2)
    assert len(chunks2) == 1
    assert (
        types.SignedBeaconBlock.deserialize(chunks2[0][1]).message.hash_tree_root()
        == root
    )

    # invalid range → error chunk
    err = handlers.on_beacon_blocks_by_range(0, 0)
    (code, msg), = decode_response_chunks(err)
    assert code == RespCode.INVALID_REQUEST
