"""Chain-service e2e: a single-process dev chain (reference analog:
`getDevBeaconNode` e2e + `dev` command, SURVEY.md §4.4) — produce blocks
from the op pools, import through the BlockProcessor pipeline with batched
signature verification, track fork choice head and finality."""

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain import BeaconChain, CpuBlsVerifier
from lodestar_tpu.chain.op_pools import AttestationPool
from lodestar_tpu.chain.seen_cache import SeenAggregatedAttestations, SeenByEpoch
from lodestar_tpu.chain.state_cache import StateContextCache
from lodestar_tpu.config.beacon_config import (
    BeaconConfig,
    ChainForkConfig,
    compute_signing_root,
)
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.params import DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER, DOMAIN_RANDAO
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.state_transition.block import _epoch_signing_root
from lodestar_tpu.types import get_types

N_VALIDATORS = 16
SPE = MINIMAL.SLOTS_PER_EPOCH


@pytest.fixture(scope="module")
def chain_env():
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, N_VALIDATORS, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    return config, types, state


def _sk(i):
    return bls.interop_secret_key(i)


def _sign_block(config, types, block):
    domain = config.get_domain(DOMAIN_BEACON_PROPOSER, block.slot)
    sig = _sk(block.proposer_index).sign(
        compute_signing_root(block.hash_tree_root(), domain)
    )
    return types.SignedBeaconBlock(message=block, signature=sig.to_bytes())


def _attest_head(config, types, chain):
    """All committees of the head slot attest to the head (full
    participation), pushed through the aggregated pool."""
    cached = chain.head_state
    state = cached.state
    slot = state.slot
    epoch = slot // SPE
    start = epoch * SPE
    head_root = chain.head_root
    if start == slot:
        target_root = head_root
    else:
        target_root = bytes(state.block_roots[start % MINIMAL.SLOTS_PER_HISTORICAL_ROOT])
    domain = config.get_domain(DOMAIN_BEACON_ATTESTER, slot, epoch)
    for index in range(cached.epoch_ctx.get_committee_count_per_slot(epoch)):
        committee = cached.epoch_ctx.get_beacon_committee(slot, index)
        data = types.AttestationData(
            slot=slot,
            index=index,
            beacon_block_root=head_root,
            source=state.current_justified_checkpoint.copy(),
            target=types.Checkpoint(epoch=epoch, root=target_root),
        )
        root = compute_signing_root(data.hash_tree_root(), domain)
        sigs = [_sk(int(v)).sign(root) for v in committee]
        att = types.Attestation(
            aggregation_bits=[True] * len(committee),
            data=data,
            signature=bls.aggregate_signatures(sigs).to_bytes(),
        )
        chain.on_aggregated_attestation(att, data.hash_tree_root())


def test_dev_chain_three_epochs_with_signatures(chain_env):
    # justification can only move at the epoch 2→3 transition (the spec
    # skips justification while current_epoch <= GENESIS_EPOCH+1), so run 3
    config, types, genesis_state = chain_env
    chain = BeaconChain(config, types, genesis_state.copy(), verifier=CpuBlsVerifier())
    from lodestar_tpu.state_transition import process_slots

    for slot in range(1, 3 * SPE + 1):
        chain.clock.set_slot(slot)
        randao_domain = config.get_domain(DOMAIN_RANDAO, slot)
        # proposer must be computed on a state advanced to `slot`
        trial = chain.head_state.copy()
        if slot > trial.state.slot:
            process_slots(trial, types, slot)
        proposer = trial.epoch_ctx.get_beacon_proposer(slot)
        reveal = _sk(proposer).sign(
            _epoch_signing_root(slot // SPE, randao_domain)
        ).to_bytes()
        block = chain.produce_block(slot, randao_reveal=reveal)
        assert block.proposer_index == proposer
        signed = _sign_block(config, types, block)
        root = chain.process_block(signed, verify_signatures=True)
        assert chain.head_root == root
        _attest_head(config, types, chain)
    assert chain.head_state.state.slot == 3 * SPE
    # full participation → epoch 2 justified at the 2→3 transition
    assert chain.justified_checkpoint[0] >= 1


def test_chain_finality_triggers_archiver(chain_env):
    """5 unsigned-verification epochs → finalization advances, archiver
    moves finalized blocks hot→cold, regen can still serve archived roots."""
    config, types, genesis_state = chain_env
    chain = BeaconChain(config, types, genesis_state.copy())
    from lodestar_tpu.state_transition import process_slots

    for slot in range(1, 5 * SPE + 1):
        chain.clock.set_slot(slot)
        trial = chain.head_state.copy()
        if slot > trial.state.slot:
            process_slots(trial, types, slot)
        proposer = trial.epoch_ctx.get_beacon_proposer(slot)
        randao_domain = config.get_domain(DOMAIN_RANDAO, slot)
        reveal = _sk(proposer).sign(
            _epoch_signing_root(slot // SPE, randao_domain)
        ).to_bytes()
        block = chain.produce_block(slot, randao_reveal=reveal)
        signed = _sign_block(config, types, block)
        chain.process_block(signed, verify_signatures=False)
        _attest_head(config, types, chain)

    fin_epoch, fin_root = chain.finalized_checkpoint
    assert fin_epoch >= 2
    # archiver moved pre-finalized canonical blocks to cold storage
    assert len(chain.finalized_blocks) > 0
    slots = [b.message.slot for b in chain.db.block_archive.values_stream()]
    assert slots == sorted(slots) and len(slots) == len(chain.finalized_blocks)
    # hot set only holds blocks at/after the finalized slot
    fin_slot = fin_epoch * SPE
    assert all(
        b is None or b.message.slot >= fin_slot for b in chain.blocks.values()
    )


def test_chain_rejects_bad_signature(chain_env):
    config, types, genesis_state = chain_env
    chain = BeaconChain(config, types, genesis_state.copy())
    from lodestar_tpu.state_transition import process_slots

    trial = chain.head_state.copy()
    process_slots(trial, types, 1)
    proposer = trial.epoch_ctx.get_beacon_proposer(1)
    randao_domain = config.get_domain(DOMAIN_RANDAO, 1)
    reveal = _sk(proposer).sign(_epoch_signing_root(0, randao_domain)).to_bytes()
    block = chain.produce_block(1, randao_reveal=reveal)
    bad = types.SignedBeaconBlock(message=block, signature=b"\x22" * 96)
    with pytest.raises(Exception):
        chain.process_block(bad, verify_signatures=True)


def test_chain_rejects_unknown_parent(chain_env):
    config, types, genesis_state = chain_env
    chain = BeaconChain(config, types, genesis_state.copy())
    block = types.BeaconBlock(
        slot=1, proposer_index=0, parent_root=b"\x99" * 32,
        state_root=b"\x00" * 32, body=types.BeaconBlockBody(),
    )
    with pytest.raises(Exception):
        chain.process_block(_sign_block(config, types, block), verify_signatures=False)


# --- unit tests for the small services --------------------------------------


def test_seen_caches():
    seen = SeenByEpoch()
    assert not seen.is_known(3, 7)
    seen.add(3, 7)
    assert seen.is_known(3, 7)
    seen.prune(4)
    assert not seen.is_known(3, 7)

    agg = SeenAggregatedAttestations()
    agg.add(1, b"r" * 32, [True, False, True])
    assert agg.is_known_superset(b"r" * 32, [True, False, False])
    assert not agg.is_known_superset(b"r" * 32, [True, True, False])


def test_state_cache_lru_eviction():
    cache = StateContextCache(max_states=2)
    cache.add(b"a" * 32, "state_a", block_root=b"A" * 32)
    cache.add(b"b" * 32, "state_b")
    assert cache.get(b"a" * 32) == "state_a"  # refresh a
    cache.add(b"c" * 32, "state_c")  # evicts b
    assert cache.get(b"b" * 32) is None
    assert cache.get_by_block_root(b"A" * 32) == "state_a"


def test_attestation_pool_aggregates(chain_env):
    config, types, _ = chain_env
    pool = AttestationPool()
    data = types.AttestationData(
        slot=5, index=0, beacon_block_root=b"h" * 32,
        source=types.Checkpoint(), target=types.Checkpoint(),
    )
    root = data.hash_tree_root()
    sk0, sk1 = _sk(0), _sk(1)
    a0 = types.Attestation(
        aggregation_bits=[True, False], data=data,
        signature=sk0.sign(b"m" * 32).to_bytes(),
    )
    a1 = types.Attestation(
        aggregation_bits=[False, True], data=data,
        signature=sk1.sign(b"m" * 32).to_bytes(),
    )
    assert pool.add(a0, root) == "added"
    assert pool.add(a1, root) == "aggregated"
    assert pool.add(a0, root) == "already_known"
    got = pool.get_aggregate(5, root)
    assert got is not None
    _, bits, agg_sig = got
    assert bits == [True, True]
    expected = bls.aggregate_signatures(
        [bls.Signature.from_bytes(a0.signature), bls.Signature.from_bytes(a1.signature)]
    )
    assert agg_sig.to_bytes() == expected.to_bytes()


def test_block_import_overlaps_payload_verification(chain_env):
    """VERDICT round-1 #7: STF ∥ signatures ∥ payload. With an execution
    engine that takes 0.4s per payload check (HTTP wait in real life),
    importing a block must NOT serialize that wait after the state
    transition — wall time stays well under (stf + sig + 0.4s)."""
    import time as _time

    from lodestar_tpu.state_transition import process_slots

    config, types, state = chain_env

    class SlowEngine:
        """Looks enough like an engine for _verify_execution_payload."""

        calls = 0
        started_at = None

        def notify_new_payload(self, payload):
            from lodestar_tpu.execution.engine import ExecutePayloadStatus

            SlowEngine.calls += 1
            SlowEngine.started_at = _time.perf_counter()
            _time.sleep(0.4)
            return ExecutePayloadStatus.VALID

    chain = BeaconChain(config, types, state.copy(), execution_engine=SlowEngine())
    # force the payload path even on a phase0 body: monkeypatch the chain's
    # payload hook to call the engine the way bellatrix import does
    orig = chain._verify_execution_payload

    def patched(post, signed_block):
        status = chain.execution_engine.notify_new_payload(None)
        from lodestar_tpu.execution.engine import ExecutePayloadStatus

        if status is not ExecutePayloadStatus.VALID:
            raise BlockImportError(str(status))

    chain._verify_execution_payload = patched
    from lodestar_tpu.chain.chain import BlockImportError  # noqa: F401

    slot = 1
    pre = chain.head_state.copy()
    process_slots(pre, types, slot)
    proposer = pre.epoch_ctx.get_beacon_proposer(slot)
    reveal = _sk(proposer).sign(
        _epoch_signing_root(0, config.get_domain(DOMAIN_RANDAO, slot))
    ).to_bytes()
    block = chain.produce_block(slot, reveal)
    signed = _sign_block(config, types, block)

    t0 = _time.perf_counter()
    chain.process_block(signed)
    wall = _time.perf_counter() - t0
    assert SlowEngine.calls == 1

    # load-robust overlap assertion: in a SERIALIZED pipeline the engine
    # call would only start after STF + signature verification, i.e. in
    # the last 0.4s of the import; overlapped, it starts right away. The
    # fraction is stable under CI contention where absolute timings are
    # not.
    start_fraction = (SlowEngine.started_at - t0) / wall
    assert start_fraction < 0.5, (start_fraction, wall)
    # and the sleep really did overlap work: the import cannot have been
    # shorter than the sleep itself
    assert wall >= 0.4


def test_regen_queue_bounded(chain_env):
    """Reference QueuedStateRegenerator bounds pending replays at 256 —
    a replay storm must reject instead of queuing unboundedly."""
    from lodestar_tpu.chain.regen import RegenError

    config, types, state = chain_env
    chain = BeaconChain(config, types, state.copy())
    chain.regen._pending = chain.regen.MAX_PENDING  # simulate a full queue
    try:
        with pytest.raises(RegenError, match="queue full"):
            chain.regen.get_state_for_block(b"\x77" * 32)
    finally:
        chain.regen._pending = 0


def test_irrecoverable_fault_window_triggers_shutdown(chain_env):
    """Reference ProcessShutdownCallback (chain.ts:121-123): more than
    allowed_faults head-selection failures inside the inspection window
    must invoke the shutdown callback; fewer must not."""
    config, types, state = chain_env
    chain = BeaconChain(config, types, state.copy())
    calls = []
    chain.process_shutdown_callback = calls.append
    chain.allowed_faults = 2
    chain.fault_inspection_window_slots = 10

    def boom():
        raise RuntimeError("no viable head")

    chain.fork_choice.update_head = boom
    for i in range(2):
        with pytest.raises(RuntimeError):
            chain.update_head()
    assert calls == []  # within budget
    with pytest.raises(RuntimeError):
        chain.update_head()
    assert calls and "irrecoverable" in calls[0]


# -- bounded serving-path waits (LODESTAR_TPU_IMPORT_WAIT_TIMEOUT) -----------


def test_bounded_wait_times_out_and_escalates(monkeypatch):
    """A never-completing future must fail the import within the bound,
    incrementing the site-labelled escalation counter — never hang."""
    from concurrent.futures import Future
    from types import SimpleNamespace

    from lodestar_tpu.chain.chain import BlockImportError, _bounded_result

    monkeypatch.setenv("LODESTAR_TPU_IMPORT_WAIT_TIMEOUT", "0.05")
    calls = []
    m = SimpleNamespace(
        blocking_wait_timeouts_total=SimpleNamespace(
            inc=lambda **labels: calls.append(labels)
        )
    )
    fut = Future()  # never resolved: a wedged EL socket / dead worker
    with pytest.raises(BlockImportError, match="IMPORT_WAIT_TIMEOUT"):
        _bounded_result(fut, "block_payload", m)
    assert calls == [{"site": "block_payload"}]


def test_bounded_wait_timeout_without_metrics_bundle(monkeypatch):
    """The bound holds even before metrics are wired (m=None)."""
    from concurrent.futures import Future

    from lodestar_tpu.chain.chain import BlockImportError, _bounded_result

    monkeypatch.setenv("LODESTAR_TPU_IMPORT_WAIT_TIMEOUT", "0.05")
    with pytest.raises(BlockImportError):
        _bounded_result(Future(), "segment_payload", None)


def test_bounded_wait_zero_disables_the_bound(monkeypatch):
    """<= 0 means unbounded (operator opt-out); a resolved future still
    returns its value immediately."""
    from concurrent.futures import Future

    from lodestar_tpu.chain.chain import _bounded_result

    monkeypatch.setenv("LODESTAR_TPU_IMPORT_WAIT_TIMEOUT", "0")
    fut = Future()
    fut.set_result("VALID")
    assert _bounded_result(fut, "block_payload", None) == "VALID"


def test_bounded_wait_passes_through_future_exception(monkeypatch):
    """A future that fails fast re-raises its own error, not a timeout."""
    from concurrent.futures import Future

    from lodestar_tpu.chain.chain import _bounded_result

    monkeypatch.setenv("LODESTAR_TPU_IMPORT_WAIT_TIMEOUT", "5")
    fut = Future()
    fut.set_exception(RuntimeError("payload INVALID"))
    with pytest.raises(RuntimeError, match="payload INVALID"):
        _bounded_result(fut, "block_payload", None)
