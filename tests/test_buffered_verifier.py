"""BufferedVerifier: the async batching front-end reproducing the
reference pool's dynamic batching (32 sigs / 100 ms window) and the
batch-failure → per-set fallback semantics (multithread/index.ts:39-57,
worker.ts:55-95)."""

import asyncio

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain.bls_verifier import (

    MAX_BUFFERED_SIGS,
    BufferedVerifier,
    CpuBlsVerifier,
)

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow


def _sets(n, salt=0, bad=()):
    out = []
    for i in range(n):
        sk = bls.interop_secret_key(i + salt)
        msg = bytes([i & 0xFF]) * 32
        signer = bls.interop_secret_key(i + salt + 500) if i in bad else sk
        out.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=signer.sign(msg).to_bytes(),
            )
        )
    return out


class CountingVerifier(CpuBlsVerifier):
    def __init__(self):
        self.batch_calls = 0
        self.individual_calls = 0

    def verify_signature_sets(self, sets):
        self.batch_calls += 1
        return super().verify_signature_sets(sets)

    def verify_signature_sets_individual(self, sets):
        self.individual_calls += 1
        return super().verify_signature_sets_individual(sets)


def test_buffer_merges_requests_into_one_batch():
    inner = CountingVerifier()
    buffered = BufferedVerifier(inner)

    async def run():
        a = asyncio.create_task(buffered.verify(_sets(2), batchable=True))
        b = asyncio.create_task(buffered.verify(_sets(2, salt=10), batchable=True))
        await asyncio.sleep(0)  # both requests enter the buffer
        buffered._flush()
        return await asyncio.gather(a, b)

    results = asyncio.run(run())
    assert results == [True, True]
    assert inner.batch_calls == 1  # merged into a single dispatch
    assert inner.individual_calls == 0


def test_buffer_flushes_at_sig_threshold():
    inner = CountingVerifier()
    buffered = BufferedVerifier(inner)

    async def run():
        # one request carrying MAX_BUFFERED_SIGS sets triggers an immediate
        # flush (no 100 ms wait)
        return await buffered.verify(_sets(MAX_BUFFERED_SIGS), batchable=True)

    assert asyncio.run(run())
    assert inner.batch_calls == 1


def test_failed_batch_falls_back_to_per_request_verdicts():
    inner = CountingVerifier()
    buffered = BufferedVerifier(inner)

    async def run():
        good = asyncio.create_task(buffered.verify(_sets(2), batchable=True))
        bad = asyncio.create_task(
            buffered.verify(_sets(2, salt=20, bad={1}), batchable=True)
        )
        await asyncio.sleep(0)
        buffered._flush()
        return await asyncio.gather(good, bad)

    results = asyncio.run(run())
    # one bad set fails ITS request only; the innocent neighbor passes
    assert results == [True, False]
    assert inner.batch_calls == 1
    assert inner.individual_calls == 1
    assert buffered.metrics["batch_fallbacks"] == 1


def test_non_batchable_bypasses_buffer():
    inner = CountingVerifier()
    buffered = BufferedVerifier(inner)

    async def run():
        return await buffered.verify(_sets(1), batchable=False)

    assert asyncio.run(run())
    assert inner.batch_calls == 1
    assert len(buffered._buffer) == 0


def test_flush_reason_counters_and_queue_gauge_transitions():
    """Size- vs timer-triggered flushes land on distinct counter series
    and the live buffer-depth gauge (callback, no polling) tracks the
    queue through both (ISSUE 1 queue observability)."""
    from lodestar_tpu.metrics import create_beacon_metrics

    inner = CountingVerifier()
    m = create_beacon_metrics()
    buffered = BufferedVerifier(inner, prom=m)
    pipeline = m.pipeline
    assert buffered.pipeline is pipeline  # inherited from the prom bundle

    async def run():
        a = asyncio.create_task(buffered.verify(_sets(2), batchable=True))
        await asyncio.sleep(0)
        assert pipeline.buffer_depth.value() == 2  # gauge went up
        # crossing MAX_BUFFERED_SIGS flushes immediately: reason=size
        b = asyncio.create_task(
            buffered.verify(_sets(MAX_BUFFERED_SIGS, salt=100), batchable=True)
        )
        await asyncio.sleep(0)
        ra, rb = await asyncio.gather(a, b)
        assert pipeline.buffer_depth.value() == 0  # ...and back down
        assert pipeline.flushes.value(reason="size") == 1
        assert pipeline.flushes.value(reason="timer") == 0
        # a lone sub-threshold request drains at the wait window: timer
        c = asyncio.create_task(buffered.verify(_sets(1, salt=200), batchable=True))
        await asyncio.sleep(0)
        assert pipeline.buffer_depth.value() == 1
        rc = await c
        assert pipeline.buffer_depth.value() == 0
        assert pipeline.flushes.value(reason="timer") == 1
        return ra, rb, rc

    assert asyncio.run(run()) == (True, True, True)
    assert pipeline.flush_seconds._totals[()] == 2  # flush latency observed


def test_device_tier_telemetry_through_thread_buffered_facade():
    """Real-kernel twin of the stubbed acceptance test in
    tests/test_observability.py: verify_signature_sets through
    ThreadBufferedVerifier over DeviceBlsVerifier on the CPU fallback
    updates a stage histogram, the planner-path counter and the
    queue-depth gauge, all visible in the /metrics text exposition."""
    from lodestar_tpu.chain.bls_verifier import (
        DeviceBlsVerifier,
        ThreadBufferedVerifier,
    )
    from lodestar_tpu.metrics import create_beacon_metrics

    m = create_beacon_metrics()
    dev = DeviceBlsVerifier(buckets=(4, 8), observer=m.pipeline)
    tbv = ThreadBufferedVerifier(dev, max_sigs=8, max_wait_ms=50, prom=m)
    # distinct roots AND keys: the planner routes the per-set kernel
    assert tbv.verify_signature_sets(_sets(3), batchable=True)

    assert m.pipeline.flushes.value(reason="timer") == 1
    assert m.pipeline.planner_decisions.value(path="per_set") == 1
    snap = m.pipeline.stage_snapshot()
    assert snap["marshal"]["count"] >= 1
    assert snap["dispatch"]["count"] >= 1
    assert snap["device_wait"]["count"] >= 1

    text = m.registry.expose()
    assert "lodestar_bls_pipeline_stage_seconds_bucket" in text
    assert 'stage="device_wait"' in text
    assert (
        'lodestar_bls_verifier_planner_decisions_total{path="per_set"} 1'
        in text
    )
    assert "lodestar_bls_verifier_buffer_depth 0" in text
    assert 'lodestar_bls_verifier_flushes_total{reason="timer"} 1' in text
