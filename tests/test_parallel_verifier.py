"""End-to-end tests of the device batch verifier vs the CPU oracle API.

Mirrors the reference's bls perf/unit shapes (verifyMultipleSignatures with
3/8 sets — beacon-node/test/perf/bls/bls.test.ts) at the functional level:
same sets must verify on both tiers, a single tampered set must fail the
batch, and the individual path must pinpoint it.
"""

import numpy as np
import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.parallel.verifier import TpuBlsVerifier

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow


_COUNTER = [0]


def _det_rng():
    # deterministic "random" coefficients for test reproducibility
    _COUNTER[0] += 1
    return (0x9E3779B97F4A7C15 * _COUNTER[0]) & ((1 << 64) - 1)


@pytest.fixture(scope="module")
def verifier():
    return TpuBlsVerifier(buckets=(4, 8), rng=_det_rng)


def _make_sets(n, salt=0):
    sets = []
    for i in range(n):
        sk = bls.interop_secret_key(i + salt)
        msg = bytes([i ^ 0xA5]) * 32
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return sets


def test_batch_verify_valid(verifier):
    sets = _make_sets(3)
    assert bls.verify_signature_sets(sets)  # oracle agrees
    assert verifier.verify_signature_sets(sets)


def test_batch_verify_detects_one_bad(verifier):
    sets = _make_sets(3)
    # signature from the wrong key on set 1
    wrong = bls.interop_secret_key(77)
    sets[1] = bls.SignatureSet(
        pubkey=sets[1].pubkey,
        message=sets[1].message,
        signature=wrong.sign(sets[1].message).to_bytes(),
    )
    assert not bls.verify_signature_sets(sets)
    assert not verifier.verify_signature_sets(sets)


def test_individual_pinpoints_bad_set(verifier):
    sets = _make_sets(3)
    wrong = bls.interop_secret_key(78)
    sets[2] = bls.SignatureSet(
        pubkey=sets[2].pubkey,
        message=sets[2].message,
        signature=wrong.sign(sets[2].message).to_bytes(),
    )
    assert verifier.verify_signature_sets_individual(sets) == [True, True, False]


def test_aggregated_pubkey_set(verifier):
    # pre-aggregated pubkey over 4 signers of one message (attestation shape)
    sks = [bls.interop_secret_key(i) for i in range(4)]
    msg = b"\x11" * 32
    agg_pk = bls.aggregate_pubkeys([sk.to_public_key() for sk in sks])
    agg_sig = bls.aggregate_signatures([sk.sign(msg) for sk in sks])
    s = bls.SignatureSet(pubkey=agg_pk, message=msg, signature=agg_sig.to_bytes())
    assert verifier.verify_signature_sets([s])


def test_empty_and_malformed(verifier):
    assert not verifier.verify_signature_sets([])
    sets = _make_sets(2)
    sets[0] = bls.SignatureSet(
        pubkey=sets[0].pubkey, message=sets[0].message, signature=b"\x00" * 96
    )
    # all-zero 96 bytes is not a valid compressed G2 encoding
    assert not verifier.verify_signature_sets(sets)


def test_bucket_padding_does_not_flip_verdict(verifier):
    # 5 sets → 8-lane bucket; 3 padding lanes must not affect the result
    sets = _make_sets(5, salt=100)
    assert verifier.verify_signature_sets(sets)
    res = verifier.verify_signature_sets_individual(sets)
    assert res == [True] * 5
