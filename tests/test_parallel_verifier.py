"""End-to-end tests of the device batch verifier vs the CPU oracle API.

Mirrors the reference's bls perf/unit shapes (verifyMultipleSignatures with
3/8 sets — beacon-node/test/perf/bls/bls.test.ts) at the functional level:
same sets must verify on both tiers, a single tampered set must fail the
batch, and the individual path must pinpoint it.
"""

import numpy as np
import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.parallel.verifier import TpuBlsVerifier

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow


_COUNTER = [0]


def _det_rng():
    # deterministic "random" coefficients for test reproducibility
    _COUNTER[0] += 1
    return (0x9E3779B97F4A7C15 * _COUNTER[0]) & ((1 << 64) - 1)


@pytest.fixture(scope="module")
def verifier():
    # device_decompress=False: these tests pin the HOST-MARSHAL path
    # (default-on since round 6 — the raw-path twins live below)
    return TpuBlsVerifier(buckets=(4, 8), rng=_det_rng, device_decompress=False)


def _make_sets(n, salt=0):
    sets = []
    for i in range(n):
        sk = bls.interop_secret_key(i + salt)
        msg = bytes([i ^ 0xA5]) * 32
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return sets


def test_batch_verify_valid(verifier):
    sets = _make_sets(3)
    assert bls.verify_signature_sets(sets)  # oracle agrees
    assert verifier.verify_signature_sets(sets)


def test_batch_verify_detects_one_bad(verifier):
    sets = _make_sets(3)
    # signature from the wrong key on set 1
    wrong = bls.interop_secret_key(77)
    sets[1] = bls.SignatureSet(
        pubkey=sets[1].pubkey,
        message=sets[1].message,
        signature=wrong.sign(sets[1].message).to_bytes(),
    )
    assert not bls.verify_signature_sets(sets)
    assert not verifier.verify_signature_sets(sets)


def test_individual_pinpoints_bad_set(verifier):
    sets = _make_sets(3)
    wrong = bls.interop_secret_key(78)
    sets[2] = bls.SignatureSet(
        pubkey=sets[2].pubkey,
        message=sets[2].message,
        signature=wrong.sign(sets[2].message).to_bytes(),
    )
    assert verifier.verify_signature_sets_individual(sets) == [True, True, False]


def test_aggregated_pubkey_set(verifier):
    # pre-aggregated pubkey over 4 signers of one message (attestation shape)
    sks = [bls.interop_secret_key(i) for i in range(4)]
    msg = b"\x11" * 32
    agg_pk = bls.aggregate_pubkeys([sk.to_public_key() for sk in sks])
    agg_sig = bls.aggregate_signatures([sk.sign(msg) for sk in sks])
    s = bls.SignatureSet(pubkey=agg_pk, message=msg, signature=agg_sig.to_bytes())
    assert verifier.verify_signature_sets([s])


def test_empty_and_malformed(verifier):
    assert not verifier.verify_signature_sets([])
    sets = _make_sets(2)
    sets[0] = bls.SignatureSet(
        pubkey=sets[0].pubkey, message=sets[0].message, signature=b"\x00" * 96
    )
    # all-zero 96 bytes is not a valid compressed G2 encoding
    assert not verifier.verify_signature_sets(sets)


def test_bucket_padding_does_not_flip_verdict(verifier):
    # 5 sets → 8-lane bucket; 3 padding lanes must not affect the result
    sets = _make_sets(5, salt=100)
    assert verifier.verify_signature_sets(sets)
    res = verifier.verify_signature_sets_individual(sets)
    assert res == [True] * 5


# --- grouped (shared-signing-root) path ------------------------------------


def _make_shared_root_sets(n, n_roots, salt=0):
    """n sets over n_roots distinct messages — committee gossip shape."""
    sets = []
    for i in range(n):
        sk = bls.interop_secret_key(i + salt)
        msg = bytes([(i % n_roots) ^ 0x3C]) * 32
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return sets


@pytest.fixture(scope="module")
def grouped_verifier():
    return TpuBlsVerifier(
        buckets=(4, 16), rng=_det_rng, grouped_configs=((4, 4),),
        device_decompress=False,
    )


def test_grouped_path_selected_for_shared_roots(grouped_verifier):
    sets = _make_shared_root_sets(10, 3)
    plan = grouped_verifier._plan_groups(sets)
    assert plan is not None
    rows_cap, lane_cap, runs = plan
    assert (rows_cap, lane_cap) == (4, 4)
    assert sorted(i for run in runs for i in run) == list(range(10))
    assert all(len(run) <= lane_cap for run in runs)
    # a root with >lane_cap sets splits across rows; one root here has 4,
    # the others 3 — 3 rows total
    assert len(runs) == 3


def test_flat_path_for_unique_roots(grouped_verifier):
    sets = _make_sets(3)  # all-distinct messages
    assert grouped_verifier._plan_groups(sets) is None


def test_grouped_verify_valid(grouped_verifier):
    sets = _make_shared_root_sets(10, 3)
    assert bls.verify_signature_sets(sets)  # oracle agrees
    assert grouped_verifier.verify_signature_sets(sets)


def test_grouped_verify_detects_one_bad(grouped_verifier):
    sets = _make_shared_root_sets(10, 3)
    wrong = bls.interop_secret_key(99)
    sets[4] = bls.SignatureSet(
        pubkey=sets[4].pubkey,
        message=sets[4].message,
        signature=wrong.sign(sets[4].message).to_bytes(),
    )
    assert not grouped_verifier.verify_signature_sets(sets)


def test_grouped_row_split_beyond_lane_cap(grouped_verifier):
    # 13 sets on ONE root: lane_cap 4 → 4 rows, same message repeated —
    # bilinearity over repeated roots must not change the verdict
    sets = _make_shared_root_sets(13, 1, salt=50)
    plan = grouped_verifier._plan_groups(sets)
    assert plan is not None and len(plan[2]) == 4
    assert grouped_verifier.verify_signature_sets(sets)


def test_grouped_malformed_signature_rejected(grouped_verifier):
    sets = _make_shared_root_sets(8, 2)
    sets[1] = bls.SignatureSet(
        pubkey=sets[1].pubkey, message=sets[1].message, signature=b"\x00" * 96
    )
    assert not grouped_verifier.verify_signature_sets(sets)


# --- adversarial-mix split (VERDICT r3 #1) ----------------------------------


def _make_unique_root_sets(n, salt=100):
    sets = []
    for i in range(n):
        sk = bls.interop_secret_key(i + salt)
        msg = bytes([i ^ 0x77, salt & 0xFF]) + b"\xEE" * 30
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return sets


def test_mixed_batch_splits_shared_from_unique(grouped_verifier):
    """Shared-root sets ride the grouped kernel; attacker-style unique
    roots go to the per-set kernel — the planner must partition, not
    degrade everything to per-set."""
    shared = _make_shared_root_sets(8, 2)
    unique = _make_unique_root_sets(8)
    sets = shared + unique
    assert grouped_verifier._plan_groups(sets) is None  # 10 roots / 16 sets
    s_idx, u_idx = grouped_verifier._split_shared_unique(sets)
    assert [sets[i] for i in s_idx] == shared
    assert [sets[i] for i in u_idx] == unique
    assert grouped_verifier.verify_signature_sets(sets) is True


def test_mixed_batch_bad_unique_set_rejected(grouped_verifier):
    shared = _make_shared_root_sets(8, 2)
    unique = _make_unique_root_sets(8)
    wrong = bls.interop_secret_key(999)
    unique[3] = bls.SignatureSet(
        pubkey=unique[3].pubkey,
        message=unique[3].message,
        signature=wrong.sign(unique[3].message).to_bytes(),
    )
    assert grouped_verifier.verify_signature_sets(shared + unique) is False


def test_mixed_batch_bad_shared_set_rejected(grouped_verifier):
    shared = _make_shared_root_sets(8, 2)
    unique = _make_unique_root_sets(8)
    wrong = bls.interop_secret_key(998)
    shared[5] = bls.SignatureSet(
        pubkey=shared[5].pubkey,
        message=shared[5].message,
        signature=wrong.sign(shared[5].message).to_bytes(),
    )
    assert grouped_verifier.verify_signature_sets(shared + unique) is False


def test_submit_resolver_pipeline(grouped_verifier):
    """submit() must return before resolution and allow a second batch
    to marshal while the first computes."""
    batch1 = _make_shared_root_sets(8, 2)
    batch2 = _make_shared_root_sets(8, 2, salt=50)
    r1 = grouped_verifier.verify_signature_sets_submit(batch1)
    r2 = grouped_verifier.verify_signature_sets_submit(batch2)
    assert r1() is True and r2() is True


def test_pubkey_cache_hits_and_verdict_stable(grouped_verifier):
    grouped_verifier._pk_cache.clear()
    sets = _make_shared_root_sets(8, 2)
    assert grouped_verifier.verify_signature_sets(sets) is True
    assert len(grouped_verifier._pk_cache) == 8
    # second pass: all cache hits, same verdict
    assert grouped_verifier.verify_signature_sets(sets) is True
    # a tampered set must still fail with a warm cache
    wrong = bls.interop_secret_key(997)
    sets[0] = bls.SignatureSet(
        pubkey=sets[0].pubkey,
        message=sets[0].message,
        signature=wrong.sign(sets[0].message).to_bytes(),
    )
    assert grouped_verifier.verify_signature_sets(sets) is False


# --- device-decompression path (raw signature bytes on device) ---------------


@pytest.fixture(scope="module")
def raw_verifier():
    return TpuBlsVerifier(
        buckets=(4, 8), grouped_configs=((4, 4),), rng=_det_rng,
        device_decompress=True,
    )


def test_raw_path_flat_valid_and_tampered(raw_verifier):
    sets = _make_sets(3)
    assert raw_verifier.verify_signature_sets(sets) is True
    wrong = bls.interop_secret_key(77)
    sets[1] = bls.SignatureSet(
        pubkey=sets[1].pubkey,
        message=sets[1].message,
        signature=wrong.sign(sets[1].message).to_bytes(),
    )
    assert raw_verifier.verify_signature_sets(sets) is False


def test_raw_path_rejects_non_subgroup_signature(raw_verifier):
    """The C tier catches out-of-subgroup signatures at marshal time; the
    device path must catch them via the batched plane check."""
    from lodestar_tpu.bls.curve import B2, PointG2, g2_to_bytes
    from lodestar_tpu.bls.fields import Fq2

    x = Fq2.from_ints(5, 1)
    while True:
        y2 = x * x * x + B2
        y = y2.sqrt()
        if y is not None:
            pt = PointG2(x, y, Fq2.one())
            if not pt.is_in_subgroup():
                break
        x = x + Fq2.from_ints(1, 0)
    sets = _make_sets(3)
    sets[2] = bls.SignatureSet(
        pubkey=sets[2].pubkey,
        message=sets[2].message,
        signature=g2_to_bytes(pt),
    )
    assert raw_verifier.verify_signature_sets(sets) is False


def test_raw_path_rejects_infinity_and_malformed(raw_verifier):
    sets = _make_sets(3)
    sets[0] = bls.SignatureSet(
        pubkey=sets[0].pubkey,
        message=sets[0].message,
        signature=bytes([0xC0]) + b"\x00" * 95,
    )
    assert raw_verifier.verify_signature_sets(sets) is False
    sets = _make_sets(3)
    sets[1] = bls.SignatureSet(
        pubkey=sets[1].pubkey, message=sets[1].message, signature=b"\x01" * 96
    )
    assert raw_verifier.verify_signature_sets(sets) is False


def test_raw_path_grouped_shared_roots(raw_verifier):
    sets = _make_shared_root_sets(12, 2, salt=20)
    assert raw_verifier.verify_signature_sets(sets) is True
    wrong = bls.interop_secret_key(996)
    sets[7] = bls.SignatureSet(
        pubkey=sets[7].pubkey,
        message=sets[7].message,
        signature=wrong.sign(sets[7].message).to_bytes(),
    )
    assert raw_verifier.verify_signature_sets(sets) is False


# --- pk-grouped (shared-pubkey, unique-root) path ---------------------------


@pytest.fixture(scope="module")
def pk_verifier():
    return TpuBlsVerifier(
        buckets=(4, 16), grouped_configs=((4, 4),),
        pk_grouped_configs=((4, 4),), rng=_det_rng,
        device_decompress=False,
    )


def _make_unique_root_shared_pk_sets(n, n_keys, salt=0):
    """n sets with UNIQUE messages over n_keys signer keys — the
    adversarial unique-AttestationData flood shape."""
    sets = []
    for i in range(n):
        sk = bls.interop_secret_key((i % n_keys) + salt)
        msg = bytes([i, i ^ 0xFF]) * 16
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return sets


def test_pk_grouping_selected_for_unique_roots(pk_verifier):
    sets = _make_unique_root_shared_pk_sets(12, 3)
    assert pk_verifier._plan_groups(sets) is None  # roots never group
    plan = pk_verifier._plan_pk_groups(sets)
    assert plan is not None
    rows_cap, lane_cap, runs = plan
    assert sum(len(r) for r in runs) == 12
    # every run holds ONE pubkey
    for run in runs:
        assert len({sets[i].pubkey.to_bytes() for i in run}) == 1
    assert pk_verifier.verify_signature_sets(sets) is True


def test_pk_grouped_detects_tampered_set(pk_verifier):
    sets = _make_unique_root_shared_pk_sets(12, 3)
    wrong = bls.interop_secret_key(55)
    sets[7] = bls.SignatureSet(
        pubkey=sets[7].pubkey,
        message=sets[7].message,
        signature=wrong.sign(sets[7].message).to_bytes(),
    )
    assert pk_verifier.verify_signature_sets(sets) is False


def test_pk_grouped_raw_path():
    v = TpuBlsVerifier(
        buckets=(4,), grouped_configs=((4, 4),),
        pk_grouped_configs=((4, 4),), rng=_det_rng,
        device_decompress=True,
    )
    sets = _make_unique_root_shared_pk_sets(12, 3, salt=30)
    assert v.verify_signature_sets(sets) is True
    wrong = bls.interop_secret_key(66)
    sets[2] = bls.SignatureSet(
        pubkey=sets[2].pubkey,
        message=sets[2].message,
        signature=wrong.sign(sets[2].message).to_bytes(),
    )
    assert v.verify_signature_sets(sets) is False


def test_pk_grouped_differential_vs_oracle(pk_verifier):
    """Planner + kernel verdicts must agree with the oracle on the same
    sets — both the valid and the tampered outcome."""
    sets = _make_unique_root_shared_pk_sets(8, 2, salt=40)
    assert bls.verify_signature_sets(sets) is True
    assert pk_verifier.verify_signature_sets(sets) is True
    wrong = bls.interop_secret_key(77)
    sets[5] = bls.SignatureSet(
        pubkey=sets[5].pubkey,
        message=sets[5].message,
        signature=wrong.sign(sets[5].message).to_bytes(),
    )
    assert bls.verify_signature_sets(sets) is False
    assert pk_verifier.verify_signature_sets(sets) is False


# --- bisection verdicts (round-6 tentpole) -----------------------------------
#
# The per-set verdict path now runs one randomized product-tree dispatch
# (root pass = all valid, ONE final exp) and binary-searches the
# materialized internal nodes on failure. Oracle-twin coverage: 0 / 1 /
# k / all-invalid mixes vs CpuBlsVerifier, invalid sets planted at
# padding-lane boundaries, and a property check that bisection verdicts
# equal the individual_verify_kernel verdicts on random batches.


@pytest.fixture(scope="module")
def bisect_observer():
    from lodestar_tpu.observability.stages import PipelineMetrics

    return PipelineMetrics()


@pytest.fixture(scope="module")
def bisect_verifier(bisect_observer):
    return TpuBlsVerifier(
        buckets=(4, 8), rng=_det_rng, device_decompress=False,
        observer=bisect_observer,
    )


def _oracle_verdicts(sets):
    from lodestar_tpu.chain.bls_verifier import CpuBlsVerifier

    return CpuBlsVerifier().verify_signature_sets_individual(sets)


def _tamper(sets, idx, key=991):
    wrong = bls.interop_secret_key(key)
    sets = list(sets)
    sets[idx] = bls.SignatureSet(
        pubkey=sets[idx].pubkey,
        message=sets[idx].message,
        signature=wrong.sign(sets[idx].message).to_bytes(),
    )
    return sets


def test_bisect_all_valid_zero_rounds(bisect_verifier, bisect_observer):
    base = bisect_observer.bisect_snapshot()
    sets = _make_sets(4, salt=300)
    out = bisect_verifier.verify_signature_sets_individual(sets)
    assert out == _oracle_verdicts(sets) == [True] * 4
    snap = bisect_observer.bisect_snapshot()
    # the all-valid common case never bisects: ONE final exp, 0 rounds
    assert snap["batches"].get("clean", 0) == base["batches"].get("clean", 0) + 1
    assert snap["rounds"] == base["rounds"]


def test_bisect_one_invalid_logn_rounds(bisect_verifier, bisect_observer):
    base = bisect_observer.bisect_snapshot()
    sets = _tamper(_make_sets(4, salt=310), 2)
    out = bisect_verifier.verify_signature_sets_individual(sets)
    assert out == _oracle_verdicts(sets) == [True, True, False, True]
    snap = bisect_observer.bisect_snapshot()
    assert snap["batches"].get("bisected", 0) == base["batches"].get("bisected", 0) + 1
    # one offender in a 4-leaf tree: exactly log2(4) = 2 rounds
    assert snap["rounds"] - base["rounds"] == 2
    assert snap["probes"] - base["probes"] > 0


def test_bisect_k_invalid_mix(bisect_verifier):
    sets = _tamper(_tamper(_make_sets(8, salt=320), 1), 6)
    out = bisect_verifier.verify_signature_sets_individual(sets)
    expect = [i not in (1, 6) for i in range(8)]
    assert out == expect == _oracle_verdicts(sets)


def test_bisect_all_invalid(bisect_verifier):
    sets = _make_sets(4, salt=330)
    for i in range(4):
        sets = _tamper(sets, i, key=900 + i)
    out = bisect_verifier.verify_signature_sets_individual(sets)
    assert out == [False] * 4 == _oracle_verdicts(sets)


def test_bisect_invalid_at_padding_boundary(bisect_verifier):
    """5 sets in the 8-lane bucket: the last REAL lane (index 4) borders
    three identity padding lanes — its subtree shares nodes with padding,
    the exact place an indexing bug would flip a verdict."""
    sets = _tamper(_make_sets(5, salt=340), 4)
    out = bisect_verifier.verify_signature_sets_individual(sets)
    assert out == [True] * 4 + [False] == _oracle_verdicts(sets)
    # first real lane for symmetry
    sets = _tamper(_make_sets(5, salt=350), 0)
    out = bisect_verifier.verify_signature_sets_individual(sets)
    assert out == [False] + [True] * 4 == _oracle_verdicts(sets)


def test_bisect_matches_individual_kernel_on_random_batches(bisect_verifier):
    """Property check: bisection verdicts == individual_verify_kernel
    verdicts on random valid/invalid mixes (the old kernel stays as the
    exact fallback and the differential anchor)."""
    import random

    r = random.Random(61)
    for trial in range(3):
        sets = _make_sets(8, salt=400 + 10 * trial)
        bad = sorted(r.sample(range(8), r.randint(0, 3)))
        for i in bad:
            sets = _tamper(sets, i, key=700 + i)
        out = bisect_verifier.verify_signature_sets_individual(sets)
        arrs = bisect_verifier._marshal(sets)
        kernel_out = [
            bool(v)
            for v in np.asarray(
                bisect_verifier.kernels.verify_individual(arrs)
            )[: arrs.n]
        ]
        assert out == kernel_out, f"trial {trial}: bad={bad}"


def test_bisect_malformed_set_uses_host_fallback(bisect_verifier):
    """A set the marshaller rejects (malformed signature encoding) must
    surface as False through the per-set host fallback, like before."""
    sets = _make_sets(3, salt=360)
    sets[1] = bls.SignatureSet(
        pubkey=sets[1].pubkey, message=sets[1].message, signature=b"\x00" * 96
    )
    out = bisect_verifier.verify_signature_sets_individual(sets)
    assert out == [True, False, True]
