"""Native KV storage engine (kvstore.c) — the LevelDB-class tier
(VERDICT round-1 missing #4): durability across reopen, crash tolerance
(torn tails, corrupt records), compaction, range iteration, values
staying OFF-heap, and the archiver/resume e2e through the beacon DB.
"""

import os
import shutil

import pytest

from lodestar_tpu import native

pytestmark = pytest.mark.skipif(
    not (native.HAVE_NATIVE and hasattr(native._mod, "kv_open")),
    reason="native KV engine not built",
)


@pytest.fixture()
def kv(tmp_path):
    from lodestar_tpu.db.controller import NativeKvDb

    db = NativeKvDb(str(tmp_path / "kv"))
    yield db
    db.close()


def test_basic_crud_and_ranges(kv):
    kv.put(b"a1", b"v1")
    kv.put(b"a2", b"v2")
    kv.put(b"b1", b"v3")
    kv.batch_put([(b"a0", b"v0"), (b"c1", b"v4")])
    assert kv.get(b"a1") == b"v1"
    assert kv.get(b"missing") is None
    kv.delete(b"a2")
    assert kv.get(b"a2") is None
    assert list(kv.keys_stream(b"a", b"b")) == [b"a0", b"a1"]
    assert list(kv.values_stream(b"a", b"c")) == [b"v0", b"v1", b"v3"]
    assert [k for k, _ in kv.entries_stream(b"", b"\xff")] == [
        b"a0", b"a1", b"b1", b"c1",
    ]
    # overwrite keeps a single entry
    kv.put(b"a1", b"v1b")
    assert kv.get(b"a1") == b"v1b"
    assert kv.stats()["entries"] == 4


def test_reopen_restores_state(tmp_path):
    from lodestar_tpu.db.controller import NativeKvDb

    path = str(tmp_path / "kv")
    db = NativeKvDb(path)
    db.put(b"k1", b"x" * 100_000)
    db.put(b"k2", b"y")
    db.delete(b"k2")
    db.put(b"k3", b"z")
    db.close()
    db = NativeKvDb(path)
    assert db.get(b"k1") == b"x" * 100_000
    assert db.get(b"k2") is None
    assert db.get(b"k3") == b"z"
    db.close()


def test_torn_tail_and_corruption_tolerated(tmp_path):
    from lodestar_tpu.db.controller import NativeKvDb

    path = str(tmp_path / "kv")
    db = NativeKvDb(path)
    db.put(b"good", b"value")
    db.put(b"later", b"value2")
    db.close()
    seg = os.path.join(path, "seg-00000.kv")
    size = os.path.getsize(seg)
    # torn tail: chop the last record mid-way
    with open(seg, "r+b") as f:
        f.truncate(size - 3)
    db = NativeKvDb(path)
    assert db.get(b"good") == b"value"
    assert db.get(b"later") is None  # torn record dropped
    # corrupt a byte of the surviving record's value: CRC must reject it
    db.close()
    with open(seg, "r+b") as f:
        f.seek(15)
        b = f.read(1)
        f.seek(15)
        f.write(bytes([b[0] ^ 0xFF]))
    db = NativeKvDb(path)
    assert db.get(b"good") is None
    db.close()


def test_compaction_reclaims_dead_space(tmp_path):
    from lodestar_tpu.db.controller import NativeKvDb

    path = str(tmp_path / "kv")
    db = NativeKvDb(path)
    for i in range(50):
        db.put(b"churn", os.urandom(4096))  # 49 dead versions
    db.put(b"keep", b"kv")
    before = db.stats()
    assert before["dead_bytes"] > 0
    db.compact()
    after = db.stats()
    assert after["dead_bytes"] == 0
    assert after["entries"] == 2
    assert db.get(b"keep") == b"kv"
    assert len(db.get(b"churn")) == 4096
    db.close()
    # compacted layout must survive reopen
    db = NativeKvDb(path)
    assert db.get(b"keep") == b"kv"
    db.close()


def test_values_stay_on_disk_not_in_memory(tmp_path):
    """The round-1 FileDb loaded every value into a Python dict; the
    native engine must keep values on disk — reopening a datadir with
    ~64MB of values must grow RSS by far less than the value bytes."""
    import resource

    from lodestar_tpu.db.controller import NativeKvDb

    path = str(tmp_path / "kv")
    db = NativeKvDb(path)
    blob = os.urandom(64 * 1024)
    for i in range(1000):  # ~64 MB of values, 1000 keys
        db.put(i.to_bytes(8, "big"), blob, )
    db.close()

    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    db = NativeKvDb(path)
    # spot reads work without loading everything
    assert db.get((7).to_bytes(8, "big")) == blob
    assert db.get((999).to_bytes(8, "big")) == blob
    rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    grew_kb = rss_after - rss_before  # ru_maxrss is KB on linux
    assert grew_kb < 16 * 1024, f"index-only reopen grew RSS by {grew_kb}KB"
    db.close()


def test_beacon_db_archiver_resume_on_native_engine(tmp_path):
    """Archiver + db resume e2e over the native engine: run a finalizing
    chain on a NativeKvDb datadir, close, reopen, and resume from the
    persisted state (VERDICT #6 'Done' criterion, minus the
    bigger-than-RAM datadir which test_values_stay_on_disk covers)."""
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.config.beacon_config import (
        BeaconConfig,
        ChainForkConfig,
        compute_signing_root,
    )
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.db import BeaconDb
    from lodestar_tpu.db.controller import NativeKvDb
    from lodestar_tpu.node.init_state import load_persisted_state, persist_state
    from lodestar_tpu.params import DOMAIN_BEACON_PROPOSER, DOMAIN_RANDAO
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.state_transition import interop_genesis_state, process_slots
    from lodestar_tpu.state_transition.block import _epoch_signing_root
    from lodestar_tpu.types import get_types
    from tests.test_chain import _attest_head

    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, 16, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    path = str(tmp_path / "kv")
    controller = NativeKvDb(path)
    db = BeaconDb(types, controller)
    chain = BeaconChain(config, types, state.copy(), db=db)
    spe = MINIMAL.SLOTS_PER_EPOCH
    for slot in range(1, 4 * spe + 1):
        chain.clock.set_slot(slot)
        trial = chain.head_state.copy()
        if slot > trial.state.slot:
            process_slots(trial, types, slot)
        proposer = trial.epoch_ctx.get_beacon_proposer(slot)
        reveal = bls.interop_secret_key(proposer).sign(
            _epoch_signing_root(slot // spe, config.get_domain(DOMAIN_RANDAO, slot))
        ).to_bytes()
        block = chain.produce_block(slot, randao_reveal=reveal)
        domain = config.get_domain(DOMAIN_BEACON_PROPOSER, slot)
        sig = bls.interop_secret_key(proposer).sign(
            compute_signing_root(block.hash_tree_root(), domain)
        )
        signed = types.SignedBeaconBlock(message=block, signature=sig.to_bytes())
        chain.process_block(signed, verify_signatures=False)
        _attest_head(config, types, chain)
    assert chain.finalized_checkpoint[0] >= 1
    head_state = chain.head_state
    head_state.sync_flat()
    persist_state(db, head_state.state, head_state.fork)
    head_slot = int(head_state.state.slot)
    controller.close()

    controller2 = NativeKvDb(path)
    db2 = BeaconDb(types, controller2)
    restored = load_persisted_state(get_types(MINIMAL), db2)
    assert restored is not None
    assert int(restored.slot) == head_slot
    # block archive survived too
    assert db2.block.get(chain.head_root) is not None
    controller2.close()


def test_compaction_crash_windows_recoverable(tmp_path):
    """The swap protocol must never lose the db: (a) .new files without a
    marker are discarded (old generation intact); (b) a marker with .new
    files finishes the promotion on open."""
    from lodestar_tpu.db.controller import NativeKvDb

    path = str(tmp_path / "kv")
    db = NativeKvDb(path)
    for i in range(10):
        db.put(f"k{i}".encode(), f"v{i}".encode())
    db.close()

    # (a) crash BEFORE the marker: stray .new must be ignored and removed
    stray = os.path.join(path, "seg-00001.kv.new")
    with open(stray, "wb") as f:
        f.write(b"\x00" * 64)
    db = NativeKvDb(path)
    assert db.get(b"k3") == b"v3"
    assert not os.path.exists(stray)
    db.close()

    # (b) crash AFTER the marker, before promotion: copy the real segment
    # to .new, delete the final, write the marker — open must promote
    seg = os.path.join(path, "seg-00000.kv")
    shutil.copy(seg, seg + ".new")
    os.unlink(seg)
    with open(os.path.join(path, "compact.done"), "w") as f:
        f.write("0\n")
        f.flush()
        os.fsync(f.fileno())
    db = NativeKvDb(path)
    assert db.get(b"k7") == b"v7"
    assert os.path.exists(seg) and not os.path.exists(seg + ".new")
    assert not os.path.exists(os.path.join(path, "compact.done"))
    db.close()


def test_auto_compaction_gate_fires_on_churn(tmp_path):
    """live/dead accounting must let the automatic gate fire: overwrite
    churn past the threshold makes kv_compact(force=0) actually run."""
    from lodestar_tpu import native
    from lodestar_tpu.db.controller import NativeKvDb

    path = str(tmp_path / "kv")
    db = NativeKvDb(path)
    blob = os.urandom(512 * 1024)
    for _ in range(40):  # ~20MB written, ~19.5MB dead, 0.5MB live
        db.put(b"churn", blob)
    st = db.stats()
    assert st["dead_bytes"] > st["live_bytes"] * 2
    ran = native._mod.kv_compact(db._h)  # gate decides, no force
    assert ran is True
    st2 = db.stats()
    assert st2["dead_bytes"] == 0
    assert db.get(b"churn") == blob
    db.close()


def test_multi_segment_compaction_invalidates_read_fd_cache(tmp_path, monkeypatch):
    """Round-2 advisor (high): compaction adopted the new segment files but
    kept the sealed-segment read-fd cache pointing at an unlinked
    pre-compaction file — a get whose entry shared the cached file_id then
    pread the dead file at new-generation offsets and returned wrong bytes.
    Force multi-segment layouts with a tiny rotation limit, warm the fd
    cache on a sealed segment, compact, and verify every read."""
    from lodestar_tpu.db.controller import NativeKvDb

    monkeypatch.setenv("LODESTAR_KV_SEG_LIMIT", "8192")  # rotate every 8KB
    path = str(tmp_path / "kv")
    db = NativeKvDb(path)
    values = {}
    for i in range(64):  # 64 x ~1KB -> ~8+ segments
        k = b"key-%03d" % i
        values[k] = os.urandom(1024)
        db.put(k, values[k])
    for i in range(0, 64, 2):  # churn: delete half to give compaction work
        db.delete(b"key-%03d" % i)
        del values[b"key-%03d" % i]
    assert db.stats()["active_segment"] > 1, "test needs a multi-segment layout"
    # warm the sealed-segment read-fd cache
    assert db.get(b"key-001") == values[b"key-001"]
    db.compact()
    assert db.stats()["active_segment"] >= 1, "compacted layout still multi-segment"
    for k, v in values.items():
        assert db.get(k) == v, f"wrong bytes for {k!r} after compaction"
    # and after the cache is re-warmed on the new generation
    for k, v in values.items():
        assert db.get(k) == v
    db.close()
    db = NativeKvDb(path)
    for k, v in values.items():
        assert db.get(k) == v
    db.close()


def test_stale_compact_tmp_cannot_resurrect_deleted_keys(tmp_path):
    """Round-3 review: a previously-failed compaction leaves segments in
    compact.tmp; the next compaction must purge them, not replay them —
    otherwise a key deleted since the failed run comes back to life."""
    import shutil

    from lodestar_tpu.db.controller import NativeKvDb

    path = str(tmp_path / "kv")
    db = NativeKvDb(path)
    db.put(b"victim", b"old-value")
    db.put(b"keeper", b"kept")
    # fabricate a failed compaction: its tmp dir holds a full copy of the
    # current (pre-delete) generation
    tmp = os.path.join(path, "compact.tmp")
    os.makedirs(tmp, exist_ok=True)
    for name in os.listdir(path):
        if name.startswith("seg-") and name.endswith(".kv"):
            shutil.copy(os.path.join(path, name), os.path.join(tmp, name))
    # the key is deleted AFTER the (simulated) failed compaction
    db.delete(b"victim")
    db.compact()
    assert db.get(b"victim") is None, "deleted key resurrected from stale tmp"
    assert db.get(b"keeper") == b"kept"
    # survives a reopen too
    db.close()
    db2 = NativeKvDb(path)
    assert db2.get(b"victim") is None
    assert db2.get(b"keeper") == b"kept"
    db2.close()
