"""Keymanager API + MEV builder API tests (reference analog:
api/src/keymanager routes, execution/builder/http.ts flows)."""

import json

import pytest

# EIP-2335 keystores (scrypt/AES) need the `cryptography` wheel, which
# minimal CI images may lack — skip, not error
pytest.importorskip("cryptography")

from lodestar_tpu.api.keymanager import create_keymanager_server
from lodestar_tpu.bls import api as bls
from lodestar_tpu.config.beacon_config import BeaconConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.db import MemoryDb
from lodestar_tpu.execution.builder import BuilderApiClient, MockBuilderRelay
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.types import get_types
from lodestar_tpu.validator import SlashingProtection, ValidatorStore
from lodestar_tpu.validator.keystore import encrypt_keystore


def _km_request(port, method, path, body=None, token=None):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        payload = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"} if payload else {}
        if token is not None:
            headers["Authorization"] = f"Bearer {token}"
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


@pytest.fixture()
def km_env():
    config = BeaconConfig(MINIMAL_CHAIN_CONFIG, b"\x00" * 32, MINIMAL)
    store = ValidatorStore(config, SlashingProtection(MemoryDb()))
    server = create_keymanager_server(store)
    server.start()
    yield store, server
    server.close()


def test_keymanager_import_list_delete(km_env):
    store, server = km_env
    sk = bls.interop_secret_key(3)
    ks = encrypt_keystore(sk.value.to_bytes(32, "big"), "pw")

    status, out = _km_request(
        server.port, "POST", "/eth/v1/keystores",
        {"keystores": [json.dumps(ks)], "passwords": ["pw"]}, token=server.bearer_token,
    )
    assert status == 200
    assert out["data"][0]["status"] == "imported"
    pk_hex = "0x" + sk.to_public_key().to_bytes().hex()

    status, out = _km_request(server.port, "GET", "/eth/v1/keystores", token=server.bearer_token)
    assert [k["validating_pubkey"] for k in out["data"]] == [pk_hex]

    # duplicate import reported as duplicate
    status, out = _km_request(
        server.port, "POST", "/eth/v1/keystores",
        {"keystores": [json.dumps(ks)], "passwords": ["pw"]}, token=server.bearer_token,
    )
    assert out["data"][0]["status"] == "duplicate"

    # delete returns slashing interchange
    # no token -> 401 (reference: keymanager API requires bearer auth)
    status, _ = _km_request(
        server.port, "DELETE", "/eth/v1/keystores", {"pubkeys": [pk_hex]}
    )
    assert status == 401
    status, out = _km_request(
        server.port, "DELETE", "/eth/v1/keystores", {"pubkeys": [pk_hex]},
        token=server.bearer_token,
    )
    assert out["data"]["statuses"][0]["status"] == "deleted"
    assert out["data"]["slashing_protection"]["metadata"]["interchange_format_version"] == "5"
    assert not store.pubkeys


def test_keymanager_wrong_password(km_env):
    store, server = km_env
    sk = bls.interop_secret_key(4)
    ks = encrypt_keystore(sk.value.to_bytes(32, "big"), "pw")
    _, out = _km_request(
        server.port, "POST", "/eth/v1/keystores",
        {"keystores": [json.dumps(ks)], "passwords": ["nope"]}, token=server.bearer_token,
    )
    assert out["data"][0]["status"] == "error"


def test_builder_flow():
    t = get_types(MINIMAL)
    relay = MockBuilderRelay()
    relay.start()
    try:
        client = BuilderApiClient("127.0.0.1", relay.port)
        assert client.check_status()

        client.register_validators(
            [{"message": {"pubkey": "0x" + b"\x01".ljust(48, b"\x00").hex()}}]
        )
        assert len(relay.registrations) == 1

        parent_hash = b"\x22" * 32
        payload = t.bellatrix.ExecutionPayload(
            parent_hash=parent_hash, block_number=7, block_hash=b"\x33" * 32
        )
        header_obj = t.bellatrix.ExecutionPayloadHeader(
            parent_hash=parent_hash, block_number=7, block_hash=b"\x33" * 32
        ).to_obj()
        relay.offer_payload(parent_hash, header_obj, payload.to_obj())

        bid = client.get_header(5, parent_hash, b"\x01" * 48)
        assert bid is not None
        header = t.bellatrix.ExecutionPayloadHeader.from_obj(bid["header"])
        assert bytes(header.parent_hash) == parent_hash

        # blinded round-trip: body carries the header; relay reveals payload
        blinded = t.bellatrix.SignedBlindedBeaconBlock(
            message=t.bellatrix.BlindedBeaconBlock(
                slot=5,
                body=t.bellatrix.BlindedBeaconBlockBody(
                    execution_payload_header=header
                ),
            ),
            signature=b"\x00" * 96,
        )
        revealed = client.submit_blinded_block(blinded.to_obj())
        got = t.bellatrix.ExecutionPayload.from_obj(revealed)
        assert got.hash_tree_root() == payload.hash_tree_root()
    finally:
        relay.close()


def test_blinded_block_root_parity():
    """A blinded block and its full block hash to the same root (the core
    invariant the builder flow depends on)."""
    t = get_types(MINIMAL)
    payload = t.bellatrix.ExecutionPayload(
        parent_hash=b"\x11" * 32,
        block_number=3,
        block_hash=b"\x44" * 32,
        transactions=[b"\xaa\xbb"],
    )
    from lodestar_tpu.state_transition.bellatrix import _field_root

    header = t.bellatrix.ExecutionPayloadHeader(
        parent_hash=b"\x11" * 32,
        block_number=3,
        block_hash=b"\x44" * 32,
        transactions_root=_field_root(payload, "transactions"),
    )
    full = t.bellatrix.BeaconBlock(
        slot=9, body=t.bellatrix.BeaconBlockBody(execution_payload=payload)
    )
    blinded = t.bellatrix.BlindedBeaconBlock(
        slot=9,
        body=t.bellatrix.BlindedBeaconBlockBody(execution_payload_header=header),
    )
    assert full.hash_tree_root() == blinded.hash_tree_root()
