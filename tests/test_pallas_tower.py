"""Differential suite for the VMEM-resident Pallas Miller tower
(ops/pallas_tower.py, ISSUE 14).

The kernel replays the exact `pairing._miller_loop_impl` jaxpr on
VMEM-resident tiles, so outputs must be BIT-identical (not merely
canonical-equal) to the XLA path — compared here under the Pallas
interpreter on CPU. Fast tier runs small shapes (one tile, padding and
the scalar-batch route); the multi-tile full-width sweep is slow tier.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.bls import curve as oc
from lodestar_tpu.ops import pairing as dp
from lodestar_tpu.ops import pallas_tower as pt
from lodestar_tpu.ops.io_host import g1_affine_to_limbs, g2_affine_to_limbs

RNG = np.random.default_rng(4242)

_ref_jit = jax.jit(
    lambda a, b, c, d: dp._miller_loop_impl(a, b, None, c, d, None)
)


def _batch(n):
    ps = [oc.PointG1.generator() * int(RNG.integers(2, 2**62)) for _ in range(n)]
    qs = [oc.PointG2.generator() * int(RNG.integers(2, 2**62)) for _ in range(n)]
    g1l = [g1_affine_to_limbs(p) for p in ps]
    g2l = [g2_affine_to_limbs(q) for q in qs]
    xp = jnp.stack([jnp.asarray(g[0]) for g in g1l])
    yp = jnp.stack([jnp.asarray(g[1]) for g in g1l])
    xq = jnp.stack([jnp.asarray(g[0]) for g in g2l])
    yq = jnp.stack([jnp.asarray(g[1]) for g in g2l])
    return (xp, yp), (xq, yq)


def test_interpret_matches_xla_small():
    # batch 3 is deliberately NOT a tile multiple: the padding lanes are
    # garbage-in/sliced-off and must not disturb the live lanes
    p, q = _batch(3)
    ref = _ref_jit(p[0], p[1], q[0], q[1])
    out = pt.miller_loop_pallas(p, q, interpret=True)
    assert out.shape == ref.shape
    assert bool(jnp.all(out == ref))  # bit-identical, pre-canonical


def test_interpret_scalar_batch_routes_through_tile():
    # the unit-batch path pads to the same MILLER_TILE shape as the
    # batched test above, so this is a jit cache hit, not a new compile
    p, q = _batch(2)
    ref = _ref_jit(p[0], p[1], q[0], q[1])
    out0 = pt.miller_loop_pallas(
        (p[0][0], p[1][0]), (q[0][0], q[1][0]), interpret=True
    )
    assert out0.shape == ref[0].shape
    assert bool(jnp.all(out0 == ref[0]))


def test_enabled_tri_state(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "0")
    assert not pt.enabled()
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "off")
    assert not pt.enabled()
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "1")
    assert pt.enabled()
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "auto")
    assert pt.enabled() == pt._on_tpu()


def test_miller_loop_dispatches_to_pallas_when_forced(monkeypatch):
    # pairing.miller_loop is the production seam: with the knob forced on
    # it must route the Pallas kernel (interpreter off-TPU) and still
    # match the XLA path limb-for-limb
    p, q = _batch(3)
    ref = _ref_jit(p[0], p[1], q[0], q[1])
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "1")
    out = dp.miller_loop(p, q)
    assert bool(jnp.all(out == ref))


@pytest.mark.slow
def test_interpret_full_width_parity():
    # multi-tile grid (2 full tiles + 1 padded): every program writes its
    # own block; full-width parity against the XLA path
    n = 2 * pt.MILLER_TILE + 1
    p, q = _batch(n)
    ref = _ref_jit(p[0], p[1], q[0], q[1])
    out = pt.miller_loop_pallas(p, q, interpret=True)
    assert bool(jnp.all(out == ref))


# --- fused full-pairing kernel (ISSUE 18) ------------------------------------
#
# pairing_fused_pallas replays the exact `_miller_loop_impl` + `fp12.mul`
# + `final_exponentiation_batch` jaxpr per PAIRING_TILE-lane tile, so the
# final-exponentiated outputs must be BIT-identical to the XLA route.
# `final_exponentiation_batch` is per-lane identical on every input
# (tests/test_final_exp_batch.py), so tiling cannot change any lane.

from lodestar_tpu.ops import fp as _fp
from lodestar_tpu.ops import fp12 as _fp12
from lodestar_tpu.ops.points import G1_GEN_X, G1_GEN_Y


def _pairing_batch(n):
    """(pk, msg, sig) affine limb stacks for n random sets (not valid
    signatures — parity needs arbitrary curve points, not verdicts)."""
    pk, msg = _batch(n)
    _, sig = _batch(n)
    return pk, msg, sig


def _ref_fused(pk, msg, sig):
    """The XLA production route: one Miller loop over 2n lanes, per-set
    product, shared-inversion batched final exp."""
    n = pk[0].shape[0]
    neg_gy = _fp.neg(G1_GEN_Y)
    xs = jnp.concatenate([pk[0], jnp.broadcast_to(G1_GEN_X, (n, 32))], 0)
    ys = jnp.concatenate([pk[1], jnp.broadcast_to(neg_gy, (n, 32))], 0)
    qx = jnp.concatenate([msg[0], sig[0]], 0)
    qy = jnp.concatenate([msg[1], sig[1]], 0)
    fs = dp._miller_loop_impl(xs, ys, None, qx, qy, None)
    return dp.final_exponentiation_batch(_fp12.mul(fs[:n], fs[n:]))


def test_pairing_enabled_tri_state(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_PAIRING", "0")
    assert not pt.pairing_enabled()
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_PAIRING", "off")
    assert not pt.pairing_enabled()
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_PAIRING", "1")
    assert pt.pairing_enabled()
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_PAIRING", "auto")
    assert pt.pairing_enabled() == pt._on_tpu()
    # the two Pallas knobs are independent: forcing the pairing knob must
    # not flip the Miller-tower dispatch, and vice versa
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "0")
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_PAIRING", "1")
    assert pt.pairing_enabled() and not pt.enabled()


def test_individual_kernel_dispatches_to_fused_when_forced(monkeypatch):
    # individual_verify_kernel is the production seam: with the knob
    # forced on it must route pairing_fused_pallas and finish with
    # is_one(fe) & valid (stubbed here — the real kernel's interpret-mode
    # parity is the slow tier below)
    from lodestar_tpu.parallel import verifier as pv

    n = 3
    calls = []

    def _stub(pk_aff, msg_aff, sig_aff, interpret=None):
        calls.append(pk_aff[0].shape)
        return _fp12.one((n,))

    monkeypatch.setenv("LODESTAR_TPU_PALLAS_PAIRING", "1")
    monkeypatch.setattr(pt, "pairing_fused_pallas", _stub)
    pk, msg, sig = _pairing_batch(n)
    valid = jnp.array([True, True, False])
    out = pv.individual_verify_kernel(
        pk[0], pk[1], msg[0], msg[1], sig[0], sig[1], valid
    )
    assert calls == [(n, 32)]
    # stubbed fe == 1 in every lane: verdicts reduce to the valid mask
    assert np.array_equal(np.asarray(out), [True, True, False])


def test_individual_kernel_ignores_fused_when_off(monkeypatch):
    from lodestar_tpu.parallel import verifier as pv

    def _boom(*a, **k):  # pragma: no cover - must not be reached
        raise AssertionError("fused path dispatched with the knob off")

    monkeypatch.setenv("LODESTAR_TPU_PALLAS_PAIRING", "0")
    monkeypatch.setattr(pt, "pairing_fused_pallas", _boom)
    pk, msg, sig = _pairing_batch(2)
    out = pv.individual_verify_kernel(
        pk[0], pk[1], msg[0], msg[1], sig[0], sig[1], jnp.array([True, True])
    )
    assert out.shape == (2,)


@pytest.mark.slow
def test_pairing_interpret_parity_one_tile():
    # one full tile: fused interpret output vs the XLA route, bit-identical
    pk, msg, sig = _pairing_batch(pt.PAIRING_TILE)
    ref = _ref_fused(pk, msg, sig)
    out = pt.pairing_fused_pallas(pk, msg, sig, interpret=True)
    assert out.shape == ref.shape
    assert bool(jnp.all(out == ref))


@pytest.mark.slow
def test_pairing_interpret_parity_padding_boundary():
    # deliberately NOT a tile multiple (2 tiles + 1 lane): the zero-point
    # padding lanes ride the final tile through the full pairing and are
    # sliced off — they must not disturb any live lane
    n = 2 * pt.PAIRING_TILE + 1
    pk, msg, sig = _pairing_batch(n)
    ref = _ref_fused(pk, msg, sig)
    out = pt.pairing_fused_pallas(pk, msg, sig, interpret=True)
    assert out.shape == ref.shape
    assert bool(jnp.all(out == ref))
