"""Differential suite for the VMEM-resident Pallas Miller tower
(ops/pallas_tower.py, ISSUE 14).

The kernel replays the exact `pairing._miller_loop_impl` jaxpr on
VMEM-resident tiles, so outputs must be BIT-identical (not merely
canonical-equal) to the XLA path — compared here under the Pallas
interpreter on CPU. Fast tier runs small shapes (one tile, padding and
the scalar-batch route); the multi-tile full-width sweep is slow tier.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lodestar_tpu.bls import curve as oc
from lodestar_tpu.ops import pairing as dp
from lodestar_tpu.ops import pallas_tower as pt
from lodestar_tpu.ops.io_host import g1_affine_to_limbs, g2_affine_to_limbs

RNG = np.random.default_rng(4242)

_ref_jit = jax.jit(
    lambda a, b, c, d: dp._miller_loop_impl(a, b, None, c, d, None)
)


def _batch(n):
    ps = [oc.PointG1.generator() * int(RNG.integers(2, 2**62)) for _ in range(n)]
    qs = [oc.PointG2.generator() * int(RNG.integers(2, 2**62)) for _ in range(n)]
    g1l = [g1_affine_to_limbs(p) for p in ps]
    g2l = [g2_affine_to_limbs(q) for q in qs]
    xp = jnp.stack([jnp.asarray(g[0]) for g in g1l])
    yp = jnp.stack([jnp.asarray(g[1]) for g in g1l])
    xq = jnp.stack([jnp.asarray(g[0]) for g in g2l])
    yq = jnp.stack([jnp.asarray(g[1]) for g in g2l])
    return (xp, yp), (xq, yq)


def test_interpret_matches_xla_small():
    # batch 3 is deliberately NOT a tile multiple: the padding lanes are
    # garbage-in/sliced-off and must not disturb the live lanes
    p, q = _batch(3)
    ref = _ref_jit(p[0], p[1], q[0], q[1])
    out = pt.miller_loop_pallas(p, q, interpret=True)
    assert out.shape == ref.shape
    assert bool(jnp.all(out == ref))  # bit-identical, pre-canonical


def test_interpret_scalar_batch_routes_through_tile():
    # the unit-batch path pads to the same MILLER_TILE shape as the
    # batched test above, so this is a jit cache hit, not a new compile
    p, q = _batch(2)
    ref = _ref_jit(p[0], p[1], q[0], q[1])
    out0 = pt.miller_loop_pallas(
        (p[0][0], p[1][0]), (q[0][0], q[1][0]), interpret=True
    )
    assert out0.shape == ref[0].shape
    assert bool(jnp.all(out0 == ref[0]))


def test_enabled_tri_state(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "0")
    assert not pt.enabled()
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "off")
    assert not pt.enabled()
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "1")
    assert pt.enabled()
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "auto")
    assert pt.enabled() == pt._on_tpu()


def test_miller_loop_dispatches_to_pallas_when_forced(monkeypatch):
    # pairing.miller_loop is the production seam: with the knob forced on
    # it must route the Pallas kernel (interpreter off-TPU) and still
    # match the XLA path limb-for-limb
    p, q = _batch(3)
    ref = _ref_jit(p[0], p[1], q[0], q[1])
    monkeypatch.setenv("LODESTAR_TPU_PALLAS_MILLER", "1")
    out = dp.miller_loop(p, q)
    assert bool(jnp.all(out == ref))


@pytest.mark.slow
def test_interpret_full_width_parity():
    # multi-tile grid (2 full tiles + 1 padded): every program writes its
    # own block; full-width parity against the XLA path
    n = 2 * pt.MILLER_TILE + 1
    p, q = _batch(n)
    ref = _ref_jit(p[0], p[1], q[0], q[1])
    out = pt.miller_loop_pallas(p, q, interpret=True)
    assert bool(jnp.all(out == ref))
