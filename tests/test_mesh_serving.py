"""Mesh-serving policy tests: dispatcher state machine + supervisor wiring.

The round-7 tentpole makes `parallel/mesh.BlsMeshDispatcher` the
production dispatch path whenever >1 chip is visible. Everything here
drives the HOST-side policy — sizing, eviction/re-admission, the
verifier compile cache, fault injection, supervisor retry — with a stub
`verifier_factory` and fake device lists, so no kernel ever compiles
(the sharded-kernel parity itself is covered by the slow tier,
tests/test_sharded_verifier.py)."""

import pytest

from lodestar_tpu.chain.supervisor import SupervisedBlsVerifier
from lodestar_tpu.observability.stages import PipelineMetrics
from lodestar_tpu.parallel.mesh import (
    NOT_SHARDED,
    BlsMeshDispatcher,
    auto_mesh,
    mesh_divisor,
)
from lodestar_tpu.testing import faults
from lodestar_tpu.testing.faults import InjectedChipFault


class _FakeGrouped:
    """Shape-only stand-in for GroupedArrays (rows, lanes)."""

    class _Arr:
        def __init__(self, shape):
            self.shape = shape

    def __init__(self, rows, lanes):
        self.pk_x = self._Arr((rows, lanes))
        self.msg_x = self._Arr((rows, lanes))


class _FakeArrs:
    """Shape-only stand-in for SetArrays (lanes)."""

    class _Arr:
        def __init__(self, shape):
            self.shape = shape

    def __init__(self, lanes):
        self.pk_x = self._Arr((lanes,))


class _StubVerifier:
    def __init__(self, kind, devices, axis):
        self.kind = kind
        self.devices = list(devices)
        self.submits = 0

    def submit(self, *args):
        self.submits += 1
        return True


def _factory_recorder(calls):
    def factory(kind, devices, axis):
        v = _StubVerifier(kind, devices, axis)
        calls.append(v)
        return v

    return factory


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear(reset_counters=True)
    yield
    faults.clear(reset_counters=True)


def _dispatcher(n_devices, observer=None, calls=None):
    calls = calls if calls is not None else []
    return BlsMeshDispatcher(
        [f"dev{i}" for i in range(n_devices)],
        observer=observer or PipelineMetrics(),
        verifier_factory=_factory_recorder(calls),
    )


def test_mesh_divisor_walks_powers_of_two():
    assert [mesh_divisor(n) for n in (1, 2, 3, 5, 7, 8, 64, 100)] == [
        1, 2, 2, 4, 4, 8, 64, 64,
    ]


def test_serving_prefix_and_sizing():
    d = _dispatcher(5)
    assert d.size == 4 and d.enabled
    assert d._serving_chips() == [0, 1, 2, 3]  # chip 4 healthy but idle
    assert _dispatcher(1).enabled is False


def test_dispatch_grouped_routes_and_counts():
    calls = []
    obs = PipelineMetrics()
    d = _dispatcher(4, observer=obs, calls=calls)
    g = _FakeGrouped(8, 64)
    assert d.dispatch_grouped(g, None, None) is True
    assert len(calls) == 1 and calls[0].kind == "grouped"
    assert calls[0].devices == ["dev0", "dev1", "dev2", "dev3"]
    # same shape: the compiled verifier is cached, not rebuilt
    assert d.dispatch_grouped(g, None, None) is True
    assert len(calls) == 1 and calls[0].submits == 2
    snap = obs.mesh_snapshot()
    assert snap["size"] == 4 and snap["evicted"] == 0
    assert snap["chip_dispatches"] == {"0": 2, "1": 2, "2": 2, "3": 2}


def test_dispatch_grouped_raw_routes_and_counts():
    """ISSUE 15: the zero-copy raw twins route through the same verifier
    cache and chip accounting as the limb kernels, under their own
    (kind, shape) cache keys."""
    calls = []
    obs = PipelineMetrics()
    d = _dispatcher(4, observer=obs, calls=calls)
    g = _FakeGrouped(8, 64)
    assert d.dispatch_grouped_raw(g, None, None, None) is True
    assert len(calls) == 1 and calls[0].kind == "grouped_raw"
    assert calls[0].devices == ["dev0", "dev1", "dev2", "dev3"]
    assert d.dispatch_pk_grouped_raw(g, None, None, None) is True
    assert len(calls) == 2 and calls[1].kind == "pk_grouped_raw"
    # same shapes again: cached verifiers, no new factory calls
    assert d.dispatch_grouped_raw(g, None, None, None) is True
    assert d.dispatch_pk_grouped_raw(g, None, None, None) is True
    assert len(calls) == 2
    assert calls[0].submits == 2 and calls[1].submits == 2
    snap = obs.mesh_snapshot()
    assert snap["chip_dispatches"] == {"0": 4, "1": 4, "2": 4, "3": 4}


def test_dispatch_raw_refuses_indivisible_and_tiny():
    d = _dispatcher(4)
    assert d.dispatch_grouped_raw(
        _FakeGrouped(9, 64), None, None, None
    ) is NOT_SHARDED
    assert d.dispatch_pk_grouped_raw(
        _FakeGrouped(6, 8), None, None, None
    ) is NOT_SHARDED
    assert _dispatcher(1).dispatch_grouped_raw(
        _FakeGrouped(8, 64), None, None, None
    ) is NOT_SHARDED


def test_dispatch_refuses_indivisible_and_tiny_batches():
    d = _dispatcher(4)
    assert d.dispatch_grouped(_FakeGrouped(9, 64), None, None) is NOT_SHARDED
    assert d.dispatch_pk_grouped(_FakeGrouped(6, 8), None, None) is NOT_SHARDED
    # bisect additionally needs the host-padded power-of-two batch
    assert d.dispatch_bisect(_FakeArrs(24), None) is NOT_SHARDED
    assert d.dispatch_bisect(_FakeArrs(16), None) is True
    # a 1-device "mesh" never shards
    assert _dispatcher(1).dispatch_grouped(
        _FakeGrouped(8, 64), None, None
    ) is NOT_SHARDED


def test_eviction_shrinks_readmission_restores():
    obs = PipelineMetrics()
    d = _dispatcher(4, observer=obs)
    assert d.evict(chip=2, reason="deadline") == 2  # 3 healthy -> size 2
    assert d.has_evicted()
    assert d._serving_chips() == [0, 1]
    # no attribution: drop the highest-index healthy chip, keep chip 0
    assert d.evict(reason="failure") == 2
    assert d._serving_chips() == [0, 1]
    assert d.evict() == 1  # 1 healthy: still evictable down to the last
    assert d.evict() is None  # nothing left to evict — caller stops
    snap = d.snapshot()
    assert snap["healthy"] == [0] and len(snap["evicted"]) == 3
    assert d.readmit() == 3
    assert not d.has_evicted() and d.size == 4
    m = obs.mesh_snapshot()
    assert m["evictions"] == {"deadline": 1, "failure": 2}
    assert m["readmissions"] == 3 and m["evicted"] == 0 and m["size"] == 4


def test_verifier_cache_keyed_by_chip_set():
    calls = []
    d = _dispatcher(4, calls=calls)
    g = _FakeGrouped(8, 64)
    d.dispatch_grouped(g, None, None)
    d.evict(chip=3)
    d.dispatch_grouped(g, None, None)  # 2-chip mesh: new compile
    assert [v.devices for v in calls] == [
        ["dev0", "dev1", "dev2", "dev3"], ["dev0", "dev1"],
    ]
    # re-admission returns to the original chip set: the old executable
    # is still cached — no third factory call
    d.readmit()
    d.dispatch_grouped(g, None, None)
    assert len(calls) == 2 and calls[0].submits == 2


def test_chip_fault_is_one_shot_and_attributed():
    d = _dispatcher(4)
    faults.configure("chip:1")
    g = _FakeGrouped(8, 64)
    with pytest.raises(InjectedChipFault) as ei:
        d.dispatch_grouped(g, None, None)
    assert ei.value.chip == 1
    # ONE-SHOT: the plan disarmed itself; the retry after eviction works
    assert d.evict(chip=ei.value.chip, reason="InjectedChipFault") == 2
    assert d.dispatch_grouped(g, None, None) is True
    assert faults.snapshot()["injected"]["chip"] == 1


def test_snapshot_shape():
    d = _dispatcher(3)
    d.dispatch_grouped(_FakeGrouped(8, 64), None, None)
    snap = d.snapshot()
    assert snap["devices_total"] == 3 and snap["size"] == 2
    assert snap["serving"] == [0, 1] and snap["dispatches"] == 1
    assert snap["compiled"] == ["grouped:8x64@2"]


# --- auto_mesh policy --------------------------------------------------------


def test_auto_mesh_env_off(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_MESH", "off")
    assert auto_mesh() is None


def test_auto_mesh_cpu_devices_need_force(monkeypatch):
    # tests run with 8 VIRTUAL cpu devices (conftest): auto must refuse —
    # silently meshing a single-host CPU backend is a cold-compile
    # regression for zero parallelism — while force opts in
    monkeypatch.setenv("LODESTAR_TPU_MESH", "auto")
    assert auto_mesh() is None
    monkeypatch.setenv("LODESTAR_TPU_MESH", "force")
    d = auto_mesh(PipelineMetrics())
    assert d is not None and d.enabled and d.size == 8


# --- supervisor wiring -------------------------------------------------------


class _FakeMeshDevice:
    """Device facade whose first N dispatches raise an attributed chip
    fault; mesh_* mirrors the dispatcher surface the supervisor uses."""

    def __init__(self, fail_chips=(2,)):
        self._pending = list(fail_chips)
        self.dispatcher = _dispatcher(4)
        self.calls = 0

    def verify_signature_sets(self, sets):
        self.calls += 1
        if self._pending:
            raise InjectedChipFault(self._pending.pop(0))
        return True

    def mesh_evict(self, chip=None, reason="failure"):
        return self.dispatcher.evict(chip=chip, reason=reason)

    def mesh_readmit(self):
        return self.dispatcher.readmit()

    def mesh_has_evicted(self):
        return self.dispatcher.has_evicted()

    def mesh_snapshot(self):
        return self.dispatcher.snapshot()


class _FakeCpu:
    def __init__(self):
        self.calls = 0

    def verify_signature_sets(self, sets):
        self.calls += 1
        return True

    def verify_signature_sets_individual(self, sets):
        self.calls += 1
        return [True] * len(sets)


def _supervised(device, **kw):
    return SupervisedBlsVerifier(
        device,
        _FakeCpu(),
        observer=PipelineMetrics(),
        deadline_s=0,  # inline dispatch: no watchdog thread in unit tests
        canary_thread=False,
        **kw,
    )


def test_supervisor_evicts_sick_chip_and_keeps_serving():
    device = _FakeMeshDevice(fail_chips=(2,))
    sup = _supervised(device)
    assert sup.verify_signature_sets([object()]) is True
    # the chip fault cost an eviction + immediate retry, NOT a CPU
    # fallback, a transient retry, or a breaker failure
    assert device.calls == 2
    assert sup.cpu.calls == 0
    assert sup.breaker_state == "closed"
    assert sup._consecutive_failures == 0
    snap = device.mesh_snapshot()
    assert [e["chip"] for e in snap["evicted"]] == [2]
    assert snap["evicted"][0]["reason"] == "InjectedChipFault"
    assert sup.breaker_snapshot()["mesh"]["size"] == 2


def test_supervisor_eviction_does_not_burn_retry_budget():
    # three successive chip faults: more than the 1-retry transient
    # budget, all absorbed by eviction retries (4 chips -> 1)
    device = _FakeMeshDevice(fail_chips=(0, 1, 2))
    sup = _supervised(device)
    assert sup.verify_signature_sets([object()]) is True
    assert device.calls == 4
    assert sup.cpu.calls == 0


def test_supervisor_falls_back_once_mesh_exhausted():
    # every dispatch raises, chips run out: the ordinary failure policy
    # takes over (transient retry, then CPU oracle) — verdicts stay
    # correct even when the whole mesh is sick
    device = _FakeMeshDevice(fail_chips=(0, 0, 0, 0, 0))
    sup = _supervised(device)
    assert sup.verify_signature_sets([object()]) is True
    assert sup.cpu.calls == 1
    # chip 0 was evicted by attribution, then the unattributed retries
    # dropped 3 and 2 from the top: chip 1 is the lone survivor
    assert device.mesh_snapshot()["healthy"] == [1]


def test_supervisor_probe_readmits_evicted_chips():
    device = _FakeMeshDevice(fail_chips=(1,))
    sup = _supervised(device)
    assert sup.verify_signature_sets([object()]) is True
    assert device.mesh_has_evicted()
    # canary probe with a healthy device: readmit-then-validate
    sup._canary_sets = [object()]
    assert sup.probe() is True
    assert not device.mesh_has_evicted()
    assert device.dispatcher.size == 4


def test_supervisor_probe_reevicts_when_full_mesh_fails():
    device = _FakeMeshDevice(fail_chips=(1,))
    sup = _supervised(device)
    assert sup.verify_signature_sets([object()]) is True

    # the canary dispatch fails WITHOUT chip attribution on the restored
    # full mesh: probe must shrink again rather than leave production on
    # a sick full mesh (and the closed breaker must stay closed)
    def bad_verify(sets):
        device.calls += 1
        raise RuntimeError("sick full mesh")

    device.verify_signature_sets = bad_verify
    sup._canary_sets = [object()]
    assert sup.probe() is False
    assert device.mesh_has_evicted()
    assert sup.breaker_state == "closed"
