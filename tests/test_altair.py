"""Altair state-transition tests: fork upgrade, participation-flag
accounting, sync aggregates, finality (reference analog: altair sanity +
finality spec suites, fork transition tests)."""

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.config.beacon_config import (
    BeaconConfig,
    ChainForkConfig,
    compute_signing_root,
)
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_SYNC_COMMITTEE,
)
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import (
    CachedBeaconState,
    interop_genesis_state,
    process_slots,
    state_transition,
)
from lodestar_tpu.state_transition.altair import upgrade_state_to_altair
from lodestar_tpu.state_transition.block import _epoch_signing_root
from lodestar_tpu.types import get_types

N = 16
SPE = MINIMAL.SLOTS_PER_EPOCH


def _sk(i):
    return bls.interop_secret_key(i)


@pytest.fixture(scope="module")
def altair_genesis():
    t = get_types(MINIMAL)
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    pre = interop_genesis_state(fork_config, t.phase0, N, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(pre.genesis_validators_root), MINIMAL
    )
    state = upgrade_state_to_altair(config, MINIMAL, pre, t.altair)
    return config, t.altair, state


def test_upgrade_to_altair(altair_genesis):
    config, types, state = altair_genesis
    assert bytes(state.fork.current_version) == config.ALTAIR_FORK_VERSION
    assert len(state.previous_epoch_participation) == N
    assert len(state.inactivity_scores) == N
    assert len(state.current_sync_committee.pubkeys) == MINIMAL.SYNC_COMMITTEE_SIZE
    assert state.current_sync_committee == state.next_sync_committee
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    assert cached.is_altair


def _sync_aggregate(config, types, cached, signing_block_root: bytes, slot: int):
    """Full-participation sync aggregate signing `signing_block_root` at
    `slot`'s previous slot."""
    prev_slot = max(slot, 1) - 1
    domain = config.get_domain(
        DOMAIN_SYNC_COMMITTEE, prev_slot, prev_slot // SPE
    )
    root = compute_signing_root(signing_block_root, domain)
    pk_to_idx = cached.epoch_ctx.pubkey_to_index
    sigs = [
        _sk(pk_to_idx[bytes(pk)]).sign(root)
        for pk in cached.state.current_sync_committee.pubkeys
    ]
    return types.SyncAggregate(
        sync_committee_bits=[True] * MINIMAL.SYNC_COMMITTEE_SIZE,
        sync_committee_signature=bls.aggregate_signatures(sigs).to_bytes(),
    )


def produce_altair_block(config, types, cached, slot, attestations, with_sync=True):
    pre = cached.copy()
    if slot > pre.state.slot:
        process_slots(pre, types, slot)
    proposer = pre.epoch_ctx.get_beacon_proposer(slot)
    sk = _sk(proposer)
    parent_root = pre.state.latest_block_header.hash_tree_root()
    body = types.BeaconBlockBody(
        randao_reveal=sk.sign(
            _epoch_signing_root(slot // SPE, config.get_domain(DOMAIN_RANDAO, slot))
        ).to_bytes(),
        eth1_data=pre.state.eth1_data.copy(),
        attestations=attestations,
    )
    if with_sync:
        body.sync_aggregate = _sync_aggregate(config, types, pre, parent_root, slot)
    block = types.BeaconBlock(
        slot=slot,
        proposer_index=proposer,
        parent_root=parent_root,
        state_root=b"\x00" * 32,
        body=body,
    )
    trial = pre.copy()
    state_transition(
        trial,
        types,
        types.SignedBeaconBlock(message=block.copy(), signature=b"\x00" * 96),
        verify_state_root=False,
        verify_signatures=False,
    )
    block.state_root = trial.state.hash_tree_root()
    domain = config.get_domain(DOMAIN_BEACON_PROPOSER, slot)
    sig = sk.sign(compute_signing_root(block.hash_tree_root(), domain))
    return types.SignedBeaconBlock(message=block, signature=sig.to_bytes())


def produce_attestations(config, types, cached, head_root):
    state = cached.state
    slot = state.slot
    epoch = slot // SPE
    start = epoch * SPE
    target_root = head_root if start == slot else bytes(
        state.block_roots[start % MINIMAL.SLOTS_PER_HISTORICAL_ROOT]
    )
    atts = []
    domain = config.get_domain(DOMAIN_BEACON_ATTESTER, slot, epoch)
    for index in range(cached.epoch_ctx.get_committee_count_per_slot(epoch)):
        committee = cached.epoch_ctx.get_beacon_committee(slot, index)
        data = types.AttestationData(
            slot=slot,
            index=index,
            beacon_block_root=head_root,
            source=state.current_justified_checkpoint.copy(),
            target=types.Checkpoint(epoch=epoch, root=target_root),
        )
        root = compute_signing_root(data.hash_tree_root(), domain)
        sigs = [_sk(int(v)).sign(root) for v in committee]
        atts.append(
            types.Attestation(
                aggregation_bits=[True] * len(committee),
                data=data,
                signature=bls.aggregate_signatures(sigs).to_bytes(),
            )
        )
    return atts


@pytest.fixture(scope="module")
def altair_finality_run(altair_genesis):
    config, types, state = altair_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    pending = []
    blocks = []
    for slot in range(1, 4 * SPE + 1):
        signed = produce_altair_block(config, types, cached, slot, pending)
        state_transition(
            cached, types, signed, verify_state_root=True, verify_signatures=False
        )
        blocks.append(signed)
        pending = produce_attestations(
            config, types, cached, signed.message.hash_tree_root()
        )
    return config, types, cached, blocks


def test_altair_finality(altair_finality_run):
    _, _, cached, _ = altair_finality_run
    assert cached.current_epoch == 4
    assert cached.state.current_justified_checkpoint.epoch >= 2
    assert cached.state.finalized_checkpoint.epoch >= 1


def test_altair_participation_and_rewards(altair_finality_run):
    _, _, cached, _ = altair_finality_run
    # full participation, no leak: zero inactivity scores, balances grow
    assert all(s == 0 for s in cached.state.inactivity_scores)
    assert min(cached.state.balances) > MINIMAL.MAX_EFFECTIVE_BALANCE
    # previous-epoch participation flags all set (source|target|head = 0b111)
    assert set(cached.state.previous_epoch_participation) == {7}


def test_altair_block_full_verification(altair_genesis):
    """One block with EVERY signature verified: proposer, randao,
    attestations, and the 32-pubkey sync aggregate (baseline config #4
    shape)."""
    config, types, state = altair_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    b1 = produce_altair_block(config, types, cached, 1, [])
    state_transition(
        cached, types, b1, verify_state_root=True, verify_signatures=True
    )
    atts = produce_attestations(config, types, cached, b1.message.hash_tree_root())
    b2 = produce_altair_block(config, types, cached, 2, atts)
    state_transition(
        cached, types, b2, verify_state_root=True, verify_signatures=True
    )
    assert cached.state.slot == 2


def test_chain_import_rejects_bad_sync_signature(altair_genesis):
    """The batched import path (chain.process_block extracts signature sets
    and runs state_transition with inline verification OFF) must include
    the sync-aggregate set — a garbage sync signature may not import."""
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.chain.chain import BlockImportError

    config, types, state = altair_genesis
    chain = BeaconChain(config, types, state.copy())
    chain.clock.set_slot(1)
    cached = chain.head_state
    good = produce_altair_block(config, types, cached, 1, [])
    bad = types.SignedBeaconBlock.deserialize(good.serialize())
    bad.message.body.sync_aggregate.sync_committee_signature = (
        _sk(99).sign(b"garbage").to_bytes()
    )
    # re-sign the block so only the sync aggregate is wrong
    bad.message.state_root = b"\x00" * 32
    trial = cached.copy()
    state_transition(
        trial, types,
        types.SignedBeaconBlock(message=bad.message.copy(), signature=b"\x00" * 96),
        verify_state_root=False, verify_signatures=False,
    )
    bad.message.state_root = trial.state.hash_tree_root()
    domain = config.get_domain(DOMAIN_BEACON_PROPOSER, 1)
    bad.signature = _sk(bad.message.proposer_index).sign(
        compute_signing_root(bad.message.hash_tree_root(), domain)
    ).to_bytes()
    with pytest.raises(BlockImportError):
        chain.process_block(bad, verify_signatures=True)
    # the honest block imports fine
    chain.process_block(good, verify_signatures=True)


def test_fork_detection_by_state_shape(altair_genesis):
    config, _, state = altair_genesis
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.state_transition.bellatrix import upgrade_state_to_bellatrix
    from lodestar_tpu.state_transition.capella import upgrade_state_to_capella

    t = get_types(MINIMAL)
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    assert cached.fork == ForkName.altair and not cached.is_execution
    bella = upgrade_state_to_bellatrix(config, MINIMAL, state.copy(), t.bellatrix)
    cached = CachedBeaconState(config, bella, MINIMAL)
    assert cached.fork == ForkName.bellatrix and cached.is_execution
    cap = upgrade_state_to_capella(config, MINIMAL, bella, t.capella)
    cached = CachedBeaconState(config, cap, MINIMAL)
    assert cached.fork == ForkName.capella and cached.is_capella


def test_sync_aggregate_bad_signature_rejected(altair_genesis):
    config, types, state = altair_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    b1 = produce_altair_block(config, types, cached, 1, [])
    # corrupt the sync signature
    b1.message.body.sync_aggregate.sync_committee_signature = (
        _sk(99).sign(b"wrong").to_bytes()
    )
    from lodestar_tpu.state_transition.block import BlockProcessingError

    with pytest.raises(BlockProcessingError):
        state_transition(
            cached, types, b1, verify_state_root=False, verify_signatures=True
        )
