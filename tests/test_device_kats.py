"""Device-tier known-answer tests that run in the DEFAULT suite.

Every other device-kernel test is slow-marked, so before round 7 the
tier-1 gate never executed a single device dispatch: a wrong-but-self-
consistent device scalar-mul (library sign + library verify agree, both
wrong) would pass tier-1 and only die in the slow tier or in production.
This module closes that hole with one tiny warm-cache bucket (4 lanes —
ONE kernel compile for the whole module, served from `.jax_cache` when
warm) driven by PINNED signature bytes:

- the aggregate / fast-aggregate KAT hexes below were produced by the
  Python oracle tier, whose scalar mul is independently cross-checked
  against an in-test affine ladder and RFC 9380 vectors
  (tests/test_spec_official.py) — so the bytes are anchored outside the
  device code entirely;
- the device verifier must ACCEPT the pinned batch and REJECT a tampered
  one. A drifted device scalar-mul, Miller loop or final exp cannot
  satisfy both: self-consistency doesn't help when the inputs are pinned
  bytes it didn't produce.
"""

import pytest

from lodestar_tpu import native
from lodestar_tpu.bls import api as bls

# (interop sk index, message, pinned signature hex) — per-set pairs
PAIR_KATS = [
    (10, b"\x40" * 32,
     "8026279efc7e27f0a69a5926666fb0762180a6962852061c0dea8c9b0cfaa290"
     "c2ba1f7061bf591231ad97457efa90f8105db1a7d79fbffc2244bc60814027a3"
     "aacf05d896cb1b9b4b34001a6e2bd1500c7b46e828667a1a284f53bd6fc090f9"),
    (11, b"\x41" * 32,
     "895dbf73414f4a6c9f2519905c44c8a87a108a0fa3f035aeba189140a9c940cc"
     "dc8241f0011686fb3c89c569a3ce69fd17304b3c280f18a91c830bad3e8c1585"
     "567d3aee2fb6a0834d052041b798c02c2c8ced3dba7d799a10a9816caef56ec8"),
    (12, b"\x42" * 32,
     "afe804241d437e1e60cd5955f3f0b02600c4802571cc8c128072abe46c9c5835"
     "699b24d40a96ad4354dd1ec4d0fa5a7205fb2359cac30baa7aa67eddd9675a6d"
     "2b66d1f25873bf3228de235e407502c8de97be4e224025f1acf7fc3db08e6f07"),
]

# fast-aggregate: interop keys 0..3 all sign FAST_AGG_MSG; the aggregate
# signature is pinned (sync-committee shape)
FAST_AGG_MSG = b"\x2a" * 32
FAST_AGG_SIG = (
    "a68f51bca0c4b79ea27d259b90a96601f12c047f786a57edd5c24813d628f302"
    "637e4f41d79082facf98615f491e4f79089c0ce2152a43ab557758100f57851d"
    "d0dab846e55b91f0dc1175d29996dd17d8eb655b36128aba5fa21dba7269d23f"
)

# aggregate-verify: interop keys 0..3 sign DISTINCT messages 0x60..0x63,
# one aggregated signature over all four (proof-of-possession aggregate)
AGG_MSGS = [bytes([0x60 + i]) * 32 for i in range(4)]
AGG_SIG = (
    "8d4fa5d956ad26820dcb18a223d0f5bb4f98fb5b4bde994915734ecc077ff314"
    "05ffe3474655559beee0f5bc6480652a199c6ca086f0a9621713792f4f450cbe"
    "60dceffa53f4c186ad194cec991b332f093c037514234c390f5d9fb269e5e266"
)


def _kat_sets():
    """The 4-lane device batch: the fast-aggregate set + 3 pinned pairs."""
    sks = [bls.interop_secret_key(i) for i in range(4)]
    agg_pk = bls.aggregate_pubkeys([sk.to_public_key() for sk in sks])
    sets = [
        bls.SignatureSet(
            pubkey=agg_pk,
            message=FAST_AGG_MSG,
            signature=bytes.fromhex(FAST_AGG_SIG),
        )
    ]
    for idx, msg, sig_hex in PAIR_KATS:
        sets.append(
            bls.SignatureSet(
                pubkey=bls.interop_secret_key(idx).to_public_key(),
                message=msg,
                signature=bytes.fromhex(sig_hex),
            )
        )
    return sets


@pytest.fixture(scope="module")
def device_verifier():
    if not native.HAVE_NATIVE_BLS:
        pytest.skip("native BLS tier unavailable (device marshal needs it)")
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    # device_decompress=False: the `*_raw` variant's on-device sqrt
    # chains (Tonelli–Shanks per point) multiply the 4-lane graph's
    # compile cost past the tier-1 budget on a cold cache; the non-raw
    # kernel carries the SAME scalar-mul / Miller / final-exp core this
    # KAT pins, at a ~4-minute-cold / seconds-warm compile. Decompress
    # correctness has its own differential fuzz (test_ops_decompress).
    return TpuBlsVerifier(buckets=(4,), device_decompress=False)


def test_fast_aggregate_kat_oracle():
    """The pinned aggregate is what the oracle tier derives today — a
    drifted aggregation or serialization fails here before the device."""
    sks = [bls.interop_secret_key(i) for i in range(4)]
    agg = bls.aggregate_signatures([sk.sign(FAST_AGG_MSG) for sk in sks])
    assert agg.to_bytes().hex() == FAST_AGG_SIG
    assert bls.fast_aggregate_verify(
        [sk.to_public_key() for sk in sks],
        FAST_AGG_MSG,
        bls.Signature.from_bytes(bytes.fromhex(FAST_AGG_SIG)),
    )


def test_aggregate_verify_kat_oracle():
    sks = [bls.interop_secret_key(i) for i in range(4)]
    agg = bls.aggregate_signatures(
        [sks[i].sign(AGG_MSGS[i]) for i in range(4)]
    )
    assert agg.to_bytes().hex() == AGG_SIG
    assert bls.aggregate_verify(
        [sk.to_public_key() for sk in sks],
        AGG_MSGS,
        bls.Signature.from_bytes(bytes.fromhex(AGG_SIG)),
    )


def test_device_accepts_pinned_kats(device_verifier):
    """The device FAST PATH (bucket 4, default configuration) must accept
    the pinned batch: its scalar mul / pairing disagreeing with the
    oracle-produced bytes in ANY direction turns this False."""
    assert device_verifier.verify_signature_sets(_kat_sets())


def test_device_rejects_tampered_kat(device_verifier):
    """...and must reject a batch whose only flaw is one swapped pinned
    signature (same shape: reuses the already-compiled 4-lane kernel)."""
    sets = _kat_sets()
    sets[1] = bls.SignatureSet(
        pubkey=sets[1].pubkey,
        message=sets[1].message,
        signature=bytes.fromhex(PAIR_KATS[2][2]),  # valid sig, wrong set
    )
    assert not device_verifier.verify_signature_sets(sets)
