"""Perf suites (reference §4.3: `test/perf/**` with @dapplion/benchmark).

Shapes mirror the reference's key suites: `bls.test.ts` (verify /
verifyMultipleSignatures 8/32 / aggregatePubkeys 32/128),
`attestation.test.ts` (validateGossipAttestation end-to-end), and
state-transition perf. Run with LODESTAR_TPU_PERF=1; by default each
case executes once (smoke) so CI stays fast — like the reference,
regression tracking is RELATIVE via the saved history file, no absolute
numbers are asserted.
"""

import os

import pytest

from lodestar_tpu.utils.benchmark import BenchRunner

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow


PERF = os.environ.get("LODESTAR_TPU_PERF") == "1"
HISTORY = os.path.join(os.path.dirname(__file__), "..", ".bench_history.json")


@pytest.fixture(scope="module")
def runner():
    r = BenchRunner(
        history_path=HISTORY if PERF else None,
        min_runs=3 if PERF else 1,
        max_seconds=3.0 if PERF else 0.0,
    )
    yield r
    if PERF:
        failures = r.check_regressions()
        r.save_history()
        assert not failures, failures
    for res in r.results:
        print(f"  {res.name}: {res.ops_per_sec:.1f} ops/s ({res.runs} runs)")


@pytest.fixture(scope="module")
def bls_sets():
    from lodestar_tpu.bls import api as bls

    sets = []
    for i in range(8):
        sk = bls.interop_secret_key(i)
        msg = bytes([i]) * 32
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return sets


def test_perf_bls_verify_single(runner, bls_sets):
    from lodestar_tpu.bls import api as bls

    s = bls_sets[0]
    sig = bls.Signature.from_bytes(s.signature)
    runner.run("bls/verify", lambda: bls.verify(s.pubkey, s.message, sig))


def test_perf_bls_verify_multiple_8(runner, bls_sets):
    from lodestar_tpu.bls import api as bls

    runner.run(
        "bls/verifyMultipleSignatures/8",
        lambda: bls.verify_signature_sets(bls_sets),
    )


def test_perf_aggregate_pubkeys_32(runner):
    from lodestar_tpu.bls import api as bls

    pks = [bls.interop_secret_key(i).to_public_key() for i in range(32)]
    runner.run("bls/aggregatePubkeys/32", lambda: bls.aggregate_pubkeys(pks))


def test_perf_gossip_attestation_validation(runner):
    """validateGossipAttestation end-to-end on a 16-validator state
    (reference attestation.test.ts:19-25 uses 64)."""
    from lodestar_tpu.chain.validation import (
        compute_subnet_for_attestation,
        validate_gossip_attestation,
    )
    from lodestar_tpu.chain.bls_verifier import MockBlsVerifier
    from lodestar_tpu.params.presets import MINIMAL
    from tests.test_network_gossip import _make_single_attestation
    from tests.test_network_live import _fresh_chain

    config, types, chain = _fresh_chain()
    chain.bls = MockBlsVerifier()  # isolate the validation ladder itself
    chain.clock.set_slot(1)
    att, _ = _make_single_attestation(config, types, chain)
    subnet = compute_subnet_for_attestation(
        chain.head_state.epoch_ctx, 0, 0, MINIMAL
    )

    def once():
        chain.seen_attesters._by_epoch.clear()  # re-validate, not IGNORE
        return validate_gossip_attestation(chain, types, att, subnet)

    result = once()
    runner.run("chain/validateGossipAttestation", once)


def test_perf_epoch_transition(runner):
    from lodestar_tpu.state_transition import process_slots
    from tests.test_network_live import _fresh_chain

    config, types, chain = _fresh_chain()
    spe = config.preset.SLOTS_PER_EPOCH

    def once():
        st = chain.head_state.copy()
        process_slots(st, types, spe)

    runner.run("state-transition/epoch-transition/16-validators", once)
