"""Gossipsub v1.1: RPC codec, score function, mesh mechanics, and live
multi-node propagation over the secure transport.

Reference analogs: `@chainsafe/libp2p-gossipsub` unit tests +
`beacon-node/test/e2e/network/gossipsub.test.ts` (two nodes exchanging
gossip objects over real libp2p).
"""

import asyncio

from lodestar_tpu.network.gossip.gossipsub import (
    Gossipsub,
    MessageCache,
    TimedSet,
    ValidationResult,
)
from lodestar_tpu.network.gossip.rpc import (
    RPC,
    ControlIHave,
    ControlPrune,
    decode_rpc,
    encode_rpc,
)
from lodestar_tpu.network.gossip.score import (
    PeerScore,
    PeerScoreParams,
    TopicScoreParams,
    ethereum_topic_params,
)


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 60.0))


# ---------------------------------------------------------------- RPC codec


def test_rpc_roundtrip_all_sections():
    rpc = RPC(
        subscriptions=[(True, "/eth2/aabbccdd/beacon_block/ssz_snappy"), (False, "t2")],
        messages=[("topicA", b"payload-1"), ("topicB", b"")],
        ihave=[ControlIHave("topicA", [b"\x01" * 20, b"\x02" * 20])],
        iwant=[b"\x03" * 20],
        graft=["topicA"],
        prune=[ControlPrune("topicB", 45)],
    )
    decoded = decode_rpc(encode_rpc(rpc))
    assert decoded.subscriptions == rpc.subscriptions
    assert decoded.messages == rpc.messages
    assert decoded.ihave[0].topic == "topicA"
    assert decoded.ihave[0].msg_ids == rpc.ihave[0].msg_ids
    assert decoded.iwant == rpc.iwant
    assert decoded.graft == ["topicA"]
    assert decoded.prune[0].topic == "topicB" and decoded.prune[0].backoff_sec == 45


def test_rpc_rejects_garbage():
    import pytest

    with pytest.raises(ValueError):
        decode_rpc(b"\xff\x01\x02")


# ---------------------------------------------------------------- mcache/seen


def test_message_cache_windows_expire():
    mc = MessageCache(gossip_windows=2, total=3)
    mc.put(b"id1", "t", b"d1")
    assert mc.gossip_ids("t") == [b"id1"]
    mc.shift()
    mc.put(b"id2", "t", b"d2")
    assert set(mc.gossip_ids("t")) == {b"id1", b"id2"}
    mc.shift()
    mc.shift()  # id1 now outside gossip windows AND expired from history
    assert mc.gossip_ids("t") == [b"id2"] or b"id1" not in mc.msgs
    mc.shift()
    assert mc.get(b"id2") is None


def test_timed_set_expiry():
    now = [0.0]
    ts = TimedSet(ttl=10.0, time_fn=lambda: now[0])
    assert ts.put(b"a") and not ts.put(b"a")
    now[0] = 11.0
    assert b"a" not in ts
    assert ts.put(b"a")


# ---------------------------------------------------------------- score


def test_score_invalid_messages_quadratic_penalty():
    now = [0.0]
    params = PeerScoreParams(topics={"t": TopicScoreParams(topic_weight=1.0)})
    score = PeerScore(params, time_fn=lambda: now[0])
    score.add_peer("p1")
    score.graft("p1", "t")
    assert score.score("p1") >= 0
    for _ in range(3):
        score.reject_message("p1", "t")
    # 9 * -100 (default invalid weight) dominates
    assert score.score("p1") < -500


def test_score_first_deliveries_reward_and_cap():
    now = [0.0]
    params = PeerScoreParams(
        topics={"t": TopicScoreParams(topic_weight=1.0, first_message_deliveries_cap=5)}
    )
    score = PeerScore(params, time_fn=lambda: now[0])
    score.add_peer("p1")
    for _ in range(50):
        score.deliver_message("p1", "t", first=True)
    assert 0 < score.score("p1") <= 5 * 1.0 + 1e-9


def test_score_retained_after_disconnect():
    now = [0.0]
    params = PeerScoreParams(topics={"t": TopicScoreParams(topic_weight=1.0)})
    score = PeerScore(params, time_fn=lambda: now[0])
    score.add_peer("bad")
    score.reject_message("bad", "t")
    before = score.score("bad")
    assert before < 0
    score.remove_peer("bad")
    score.add_peer("bad")  # reconnect: penalty must survive
    assert score.score("bad") == before


def test_ethereum_topic_params_shape():
    bb = ethereum_topic_params("beacon_block")
    att = ethereum_topic_params("beacon_attestation")
    assert bb.topic_weight > att.topic_weight
    assert bb.invalid_message_deliveries_weight < 0


# ---------------------------------------------------------------- router unit


class _Pipe:
    """Connect two routers in-memory."""

    def __init__(self):
        self.routers = {}

    def add(self, name: str, router: Gossipsub):
        self.routers[name] = router

    def link(self, a: str, b: str, outbound_a=True):
        ra, rb = self.routers[a], self.routers[b]

        async def send_to_b(data: bytes):
            await rb.on_rpc(a, data)

        async def send_to_a(data: bytes):
            await ra.on_rpc(b, data)

        ra.add_peer(b, send_to_b, outbound=outbound_a)
        rb.add_peer(a, send_to_a, outbound=not outbound_a)


def test_mesh_forms_and_message_propagates():
    async def main():
        pipe = _Pipe()
        routers = {n: Gossipsub() for n in ("a", "b", "c")}
        for n, r in routers.items():
            pipe.add(n, r)
        pipe.link("a", "b")
        pipe.link("b", "c")
        got = []

        for n, r in routers.items():
            await r.subscribe("topic1")

        async def tap(topic, data):
            got.append(data)

        routers["c"].on_message = tap
        for r in routers.values():
            await r.heartbeat()
        # a publishes; c (two hops away) must receive via b's mesh forward
        await routers["a"].publish("topic1", b"hello-mesh")
        await asyncio.sleep(0)
        assert got == [b"hello-mesh"]
        # duplicate publish is suppressed by the seen cache
        sent = await routers["a"].publish("topic1", b"hello-mesh")
        assert sent == 0

    run(main())


def test_reject_validation_stops_propagation_and_penalizes():
    async def main():
        pipe = _Pipe()
        a, b, c = Gossipsub(), Gossipsub(), Gossipsub()
        pipe.add("a", a), pipe.add("b", b), pipe.add("c", c)
        pipe.link("a", "b")
        pipe.link("b", "c")
        for r in (a, b, c):
            await r.subscribe("t")
            await r.heartbeat()

        async def reject_all(topic, data):
            return ValidationResult.REJECT

        b.validators["t"] = reject_all
        got = []

        async def tap(topic, data):
            got.append(data)

        c.on_message = tap
        b.score.params.topics["t"] = TopicScoreParams(topic_weight=1.0)
        await a.publish("t", b"bad-message")
        await asyncio.sleep(0)
        assert got == []  # b refused to forward
        assert b.score.score("a") < 0  # and penalized the sender

    run(main())


def test_ihave_iwant_recovery():
    async def main():
        pipe = _Pipe()
        a, b = Gossipsub(), Gossipsub()
        pipe.add("a", a), pipe.add("b", b)
        # linked, subscribed, but NOT meshed (no heartbeat joins yet):
        # direct publish only reaches mesh/flood targets — emulate a missed
        # message by injecting into a's mcache alone
        pipe.link("a", "b")
        await a.subscribe("t")
        # keep b OUT of a's mesh (prune backoff): IHAVE goes only to
        # non-mesh topic peers — mesh members get the messages themselves
        a.peers["b"].dont_send_until["t"] = 1e18
        await b.subscribe("t")
        a.mesh["t"].discard("b")  # drop any graft that raced the backoff
        from lodestar_tpu.network.gossip.encoding import compute_msg_id

        data = b"missed-message"
        mid = compute_msg_id("t", data)
        a.seen.put(mid)
        a.mcache.put(mid, "t", data)
        got = []

        async def tap(topic, d):
            got.append(d)

        b.on_message = tap
        # a's heartbeat emits IHAVE to b → b IWANTs → a sends the message
        await a.heartbeat()
        await asyncio.sleep(0)
        assert got == [data]

    run(main())


def test_prune_backoff_respected():
    async def main():
        now = [0.0]
        a = Gossipsub(time_fn=lambda: now[0])
        sent = []

        async def send(data):
            sent.append(decode_rpc(data))

        a.add_peer("p", send, outbound=True)
        a.peers["p"].topics.add("t")
        await a.subscribe("t")
        # peer prunes us with a 60s backoff
        await a.on_rpc("p", encode_rpc(RPC(prune=[ControlPrune("t", 60)])))
        sent.clear()
        await a.heartbeat()
        grafts = [r for r in sent if r.graft]
        assert not grafts  # must not re-graft during backoff
        now[0] = 61.0
        await a.heartbeat()
        grafts = [r for r in sent if r.graft]
        assert grafts  # backoff expired → graft again

    run(main())


def test_iwant_served_with_budget_and_score_gate():
    """Round-1 advisor low: IWANT service is capped per peer per heartbeat
    and gated on peer score — a bandwidth-sink peer cannot drain the
    mcache repeatedly within one heartbeat."""

    async def main():
        from lodestar_tpu.network.gossip.encoding import compute_msg_id
        from lodestar_tpu.network.gossip.gossipsub import (
            MAX_IWANT_SERVED_PER_HEARTBEAT,
        )

        a = Gossipsub()
        served = []

        async def sink(data: bytes):
            rpc = decode_rpc(data)
            served.extend(rpc.messages)

        a.add_peer("leech", sink, outbound=False)
        await a.subscribe("t")
        mids = []
        n = MAX_IWANT_SERVED_PER_HEARTBEAT + 50
        for i in range(n):
            data = b"m%d" % i
            mid = compute_msg_id("t", data)
            a.mcache.put(mid, "t", data)
            mids.append(mid)

        await a.on_rpc("leech", encode_rpc(RPC(iwant=list(mids))))
        assert len(served) == MAX_IWANT_SERVED_PER_HEARTBEAT  # capped
        # budget exhausted within the heartbeat: nothing more is served
        served.clear()
        await a.on_rpc("leech", encode_rpc(RPC(iwant=list(mids))))
        assert served == []
        # reconnect churn must NOT refresh the budget mid-heartbeat
        a.remove_peer("leech")
        a.add_peer("leech", sink, outbound=False)
        served.clear()
        await a.on_rpc("leech", encode_rpc(RPC(iwant=list(mids))))
        assert served == []
        # heartbeat refreshes the budget
        await a.heartbeat()
        served.clear()
        await a.on_rpc("leech", encode_rpc(RPC(iwant=list(mids[:4]))))
        assert len(served) == 4
        # the budget counts SERVED messages: uncached ids don't consume it
        await a.heartbeat()
        served.clear()
        missing = [b"\x99" * 20] * MAX_IWANT_SERVED_PER_HEARTBEAT
        await a.on_rpc("leech", encode_rpc(RPC(iwant=missing + mids[4:8])))
        assert len(served) == 4
        # graylisted peers are not served at all
        a.score.params.topics["t"] = TopicScoreParams(topic_weight=1.0)
        for _ in range(50):
            a.score.reject_message("leech", "t")
        served.clear()
        await a.on_rpc("leech", encode_rpc(RPC(iwant=list(mids[:4]))))
        assert served == []

    run(main())
