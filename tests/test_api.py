"""Beacon API e2e: real HTTP server + generated client against a live
chain (reference analog: beacon-node api e2e + api package unit tests)."""

import pytest

from lodestar_tpu.api import BeaconApiClient, BeaconApiServer
from lodestar_tpu.api.impl import BeaconApiImpl
from lodestar_tpu.api.routes import match_route
from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain import BeaconChain
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.db import MemoryDb
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.types import get_types
from lodestar_tpu.validator import (
    SlashingProtection,
    ValidatorService,
    ValidatorStore,
)

N = 16
SPE = MINIMAL.SLOTS_PER_EPOCH


def test_match_route():
    r, params = match_route("GET", "/eth/v1/beacon/states/head/root")
    assert r is not None and r.operation_id == "getStateRoot"
    assert params == {"state_id": "head"}
    r2, _ = match_route("GET", "/eth/v1/nonexistent")
    assert r2 is None


@pytest.fixture(scope="module")
def api_env():
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    chain = BeaconChain(config, types, state)
    store = ValidatorStore(config, SlashingProtection(MemoryDb()))
    for i in range(N):
        store.add_secret_key(bls.interop_secret_key(i))
    service = ValidatorService(config, types, chain, store)
    impl = BeaconApiImpl(config, types, chain, validator_service=service)
    server = BeaconApiServer(impl, port=0)
    server.start()
    client = BeaconApiClient(port=server.port)
    yield config, types, chain, service, client
    server.close()


def test_genesis_and_node_endpoints(api_env):
    config, _, chain, _, client = api_env
    g = client.getGenesis()
    assert g["genesis_time"] == str(chain.head_state.state.genesis_time)
    assert g["genesis_validators_root"].startswith("0x")
    v = client.getNodeVersion()
    assert "lodestar-tpu" in v["version"]
    spec = client.getSpec()
    assert spec["PRESET_BASE"] == "minimal"


def test_state_and_validator_endpoints(api_env):
    _, _, chain, _, client = api_env
    root = client.getStateRoot("head")
    assert bytes.fromhex(root["root"][2:]) == chain.head_state.state.hash_tree_root()
    cps = client.getStateFinalityCheckpoints("head")
    assert cps["finalized"]["epoch"] == "0"
    vals = client.getStateValidators("head")
    assert len(vals) == N
    assert vals[0]["status"] == "active_ongoing"
    one = client.getStateValidator("head", "3")
    assert one["index"] == "3"
    by_pk = client.getStateValidator("head", one["validator"]["pubkey"])
    assert by_pk["index"] == "3"


def test_duties_and_block_production_flow(api_env):
    config, types, chain, service, client = api_env
    duties = client.getAttesterDuties("0", body=[str(i) for i in range(N)])
    assert len(duties) == N
    proposer_duties = client.getProposerDuties("0")
    assert len(proposer_duties) == SPE

    # produce a block via REST, sign locally, publish via REST
    slot = 1
    chain.clock.set_slot(slot)
    duty = next(d for d in proposer_duties if int(d["slot"]) == slot)
    pk = bytes.fromhex(duty["pubkey"][2:])
    reveal = service.store.sign_randao(pk, slot)
    produced = client.produceBlockV2(str(slot), query={"randao_reveal": "0x" + reveal.hex()})
    block = types.BeaconBlock.from_obj(produced["data"])
    signed = service.store.sign_block(pk, types, block)
    client.publishBlock(body=signed.to_obj())
    assert chain.head_state.state.slot == slot

    # block queries reflect the publish
    hdr = client.getBlockHeader("head")
    assert hdr["header"]["message"]["slot"] == str(slot)
    blk = client.getBlockV2("head")
    assert blk["data"]["message"]["slot"] == str(slot)

    # attestation data + pool round trip
    att_data = client.produceAttestationData(
        query={"slot": str(slot), "committee_index": "0"}
    )
    assert att_data["slot"] == str(slot)


def test_error_paths(api_env):
    _, _, _, _, client = api_env
    from lodestar_tpu.api.client import ApiClientError

    with pytest.raises(ApiClientError) as ei:
        client.getStateValidator("head", "9999")
    assert ei.value.status == 404
    with pytest.raises(ApiClientError):
        client.getBlockV2("0x" + "ab" * 32)


def test_prepare_beacon_proposer_feeds_block_production(api_env):
    """prepareBeaconProposer registrations land in the proposer cache and
    produce_block picks the registered fee recipient (reference
    beaconProposerCache flow)."""
    config, types, chain, _service, client = api_env
    fee = bytes(range(20))
    entries = [
        {"validator_index": str(i), "fee_recipient": "0x" + fee.hex()}
        for i in range(len(chain.head_state.state.validators))
    ]
    client.prepareBeaconProposer(body=entries)
    assert len(chain.beacon_proposer_cache) == len(entries)
    assert chain.beacon_proposer_cache.get(0) == fee
    # pruning drops stale registrations
    chain.beacon_proposer_cache.prune(current_epoch=10)
    assert len(chain.beacon_proposer_cache) == 0
    assert chain.beacon_proposer_cache.get(0) == b"\x00" * 20


def test_event_stream_sse(api_env):
    """SSE /eth/v1/events delivers head/block events fired by block import
    (reference events.ts + eventSource.ts)."""
    import queue
    import threading

    from lodestar_tpu.api.client import stream_events
    from tests.test_chain import _sign_block, _sk
    from lodestar_tpu.state_transition import process_slots
    from lodestar_tpu.state_transition.block import _epoch_signing_root
    from lodestar_tpu.params import DOMAIN_RANDAO

    config, types, chain, _service, client = api_env
    got: "queue.Queue" = queue.Queue()

    def consume():
        try:
            for name, payload in stream_events(
                "127.0.0.1", client.port, topics=["head", "block"], timeout=15
            ):
                got.put((name, payload))
        except Exception as e:
            got.put(("error", {"message": str(e)}))

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    import time as _time

    _time.sleep(0.3)  # let the subscriber attach

    slot = chain.head_state.state.slot + 1
    chain.clock.set_slot(slot)
    trial = chain.head_state.copy()
    if slot > trial.state.slot:
        process_slots(trial, types, slot)
    proposer = trial.epoch_ctx.get_beacon_proposer(slot)
    reveal = _sk(proposer).sign(
        _epoch_signing_root(slot // config.preset.SLOTS_PER_EPOCH,
                            config.get_domain(DOMAIN_RANDAO, slot))
    ).to_bytes()
    block = chain.produce_block(slot, randao_reveal=reveal)
    signed = _sign_block(config, types, block)
    chain.process_block(signed, verify_signatures=False)

    names = set()
    for _ in range(2):
        try:
            name, payload = got.get(timeout=10)
        except queue.Empty:
            break
        names.add(name)
    assert "block" in names or "head" in names, f"no events received: {names}"


def test_bearer_auth_and_cors(api_env):
    """Reference parity: fastify bearer-auth + cors registration
    (`beacon-node/src/api/rest/index.ts:47-60`)."""
    import http.client

    config, types, chain, service, _ = api_env
    impl = BeaconApiImpl(config, types, chain, validator_service=service)
    server = BeaconApiServer(
        impl, port=0, bearer_token="s3cret", cors_origin="https://ui.example"
    )
    server.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=5)
        # no token → 401
        conn.request("GET", "/eth/v1/beacon/genesis")
        resp = conn.getresponse()
        assert resp.status == 401
        resp.read()
        # wrong token → 401
        conn.request(
            "GET", "/eth/v1/beacon/genesis",
            headers={"Authorization": "Bearer nope"},
        )
        resp = conn.getresponse()
        assert resp.status == 401
        resp.read()
        # right token → 200, with CORS header
        conn.request(
            "GET", "/eth/v1/beacon/genesis",
            headers={"Authorization": "Bearer s3cret"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Access-Control-Allow-Origin") == "https://ui.example"
        resp.read()
        # preflight needs no token and advertises methods
        conn.request("OPTIONS", "/eth/v1/beacon/genesis")
        resp = conn.getresponse()
        assert resp.status == 204
        assert "POST" in resp.getheader("Access-Control-Allow-Methods", "")
        assert resp.getheader("Access-Control-Allow-Origin") == "https://ui.example"
        resp.read()
        conn.close()
    finally:
        server.close()
