"""BLS12-381 oracle tests.

Reference analogs: bls12-381-tests vectors (spec suite, not downloadable in
this environment) are replaced by: (a) algebraic invariants (bilinearity,
orders, subgroup laws), (b) cross-implementation vectors embedded in the
reference repo (interop deposit signature — blst-produced), and (c) an RFC
9380 expand_message_xmd known-answer vector.
"""

import hashlib

import pytest

from lodestar_tpu.bls import (
    CURVE_ORDER,
    PublicKey,
    SecretKey,
    Signature,
    SignatureSet,
    aggregate_signatures,
    aggregate_verify,
    fast_aggregate_verify,
    interop_secret_key,
    verify,
    verify_signature_sets,
)
from lodestar_tpu.bls.curve import PointG1, PointG2, g1_from_bytes, g1_to_bytes
from lodestar_tpu.bls.hash_to_curve import expand_message_xmd
from lodestar_tpu.bls.pairing import (
    final_exponentiation,
    final_exponentiation_naive,
    miller_loop,
    pairing,
)


def test_generators():
    g1, g2 = PointG1.generator(), PointG2.generator()
    assert g1.is_on_curve() and g2.is_on_curve()
    assert (g1 * CURVE_ORDER).is_infinity()
    assert (g2 * CURVE_ORDER).is_infinity()
    # canonical compressed G1 generator
    assert g1_to_bytes(g1).hex().startswith("97f1d3a73197d794")


def test_point_serialization_errors():
    with pytest.raises(ValueError):
        g1_from_bytes(b"\x00" * 48)  # C flag unset
    with pytest.raises(ValueError):
        g1_from_bytes(b"\xc0" + b"\x01" + b"\x00" * 46)  # malformed infinity
    # x >= p rejected
    with pytest.raises(ValueError):
        g1_from_bytes(b"\x9f" + b"\xff" * 47)


def test_pairing_bilinearity():
    g1, g2 = PointG1.generator(), PointG2.generator()
    assert pairing(g1 * 6, g2 * 5) == pairing(g1 * 3, g2 * 10)
    assert pairing(g1, g2 * 7) == pairing(g1 * 7, g2)
    assert not pairing(g1, g2).is_one()


def test_fast_final_exp_is_cube_of_naive():
    f = miller_loop(PointG1.generator(), PointG2.generator())
    assert final_exponentiation(f) == final_exponentiation_naive(f).pow(3)


def test_expand_message_xmd_rfc9380_vector():
    # RFC 9380 Appendix K.1 (SHA-256): msg="", len_in_bytes=0x20
    out = expand_message_xmd(b"", b"QUUX-V01-CS02-with-expander-SHA256-128", 0x20)
    assert out.hex() == "68a985b87eb6b46952128911f2a4412bbc302a9d759667f87f7a21d803f07235"


def test_interop_deposit_signature_vector():
    """Byte-for-byte reproduction of the blst-produced interop deposit
    signature embedded in the reference
    (beacon-node/test/e2e/interop/genesisState.test.ts): validates interop
    keygen, G1, SSZ signing root, hash-to-curve (incl. isogeny + cofactor
    clearing), signing, and G2 serialization as RFC-exact."""
    from lodestar_tpu.config import compute_domain, compute_signing_root
    from lodestar_tpu.params import DOMAIN_DEPOSIT
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.types import get_types

    t = get_types(MINIMAL)
    sk = interop_secret_key(0)
    pk = sk.to_public_key().to_bytes()
    assert pk.hex() == (
        "a99a76ed7796f7be22d5b7e85deeb7c5677e88e511e0b337618f8c4eb61349b4"
        "bf2d153f649f7b53359fe8b94a38e44c"
    )
    wc = b"\x00" + hashlib.sha256(pk).digest()[1:]
    msg = t.phase0.DepositMessage(pubkey=pk, withdrawal_credentials=wc, amount=32_000_000_000)
    # minimal-preset GENESIS_FORK_VERSION (reference e2e runs minimal)
    domain = compute_domain(DOMAIN_DEPOSIT, bytes.fromhex("00000001"), b"\x00" * 32)
    signing_root = compute_signing_root(msg.hash_tree_root(), domain)
    sig = sk.sign(signing_root)
    assert sig.to_bytes().hex() == (
        "a95af8ff0f8c06af4d29aef05ce865f85f82df42b606008ec5b1bcb42b17ae47"
        "f4b78cdce1db31ce32d18f42a6b296b4014a2164981780e56b5a40d7723c27b8"
        "423173e58fa36f075078b177634f66351412b867c103f532aedd50bcd9b98446"
    )
    assert verify(sk.to_public_key(), signing_root, sig)


def test_sign_verify_roundtrip():
    sk = interop_secret_key(3)
    msg = b"\x11" * 32
    sig = sk.sign(msg)
    assert verify(sk.to_public_key(), msg, sig)
    assert not verify(sk.to_public_key(), b"\x22" * 32, sig)
    assert not verify(interop_secret_key(4).to_public_key(), msg, sig)


def test_signature_deserialize_validates():
    sk = interop_secret_key(5)
    sig = sk.sign(b"\x00" * 32)
    assert Signature.from_bytes(sig.to_bytes()) == sig
    with pytest.raises(ValueError):
        Signature.from_bytes(b"\x00" * 96)


def test_aggregate_verify():
    sks = [interop_secret_key(i) for i in range(3)]
    msgs = [bytes([i]) * 32 for i in range(3)]
    sigs = [sk.sign(m) for sk, m in zip(sks, msgs)]
    agg = aggregate_signatures(sigs)
    pks = [sk.to_public_key() for sk in sks]
    assert aggregate_verify(pks, msgs, agg)
    assert not aggregate_verify(pks, list(reversed(msgs)), agg)
    assert not aggregate_verify(pks[:2], msgs, agg)


def test_fast_aggregate_verify():
    sks = [interop_secret_key(i) for i in range(4)]
    msg = b"\xab" * 32
    agg = aggregate_signatures([sk.sign(msg) for sk in sks])
    pks = [sk.to_public_key() for sk in sks]
    assert fast_aggregate_verify(pks, msg, agg)
    assert not fast_aggregate_verify(pks[:3], msg, agg)
    assert not fast_aggregate_verify([], msg, agg)


def test_batch_verify_signature_sets():
    sets = []
    for i in range(4):
        sk = interop_secret_key(i)
        msg = bytes([i * 7]) * 32
        sets.append(
            SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    assert verify_signature_sets(sets)
    # one corrupted set fails the whole batch
    bad = SignatureSet(
        pubkey=sets[0].pubkey,
        message=b"\xff" * 32,
        signature=sets[0].signature,
    )
    assert not verify_signature_sets(sets[:3] + [bad])
    assert not verify_signature_sets([])


def test_batch_matches_individual():
    """Batch accepting implies each set verifies individually (statistically);
    here just cross-check agreement on a valid + an invalid batch."""
    sk = interop_secret_key(9)
    msg = b"\x42" * 32
    good = SignatureSet(sk.to_public_key(), msg, sk.sign(msg).to_bytes())
    assert verify_signature_sets([good]) == verify(sk.to_public_key(), msg, sk.sign(msg))
    swapped = SignatureSet(
        interop_secret_key(10).to_public_key(), msg, sk.sign(msg).to_bytes()
    )
    assert not verify_signature_sets([swapped])


def test_keygen():
    sk = SecretKey.from_keygen(b"\x01" * 32)
    sk2 = SecretKey.from_keygen(b"\x01" * 32)
    assert sk.value == sk2.value  # deterministic from ikm
    assert 0 < sk.value < CURVE_ORDER
    msg = b"\x00" * 32
    assert verify(sk.to_public_key(), msg, sk.sign(msg))


def test_pubkey_validate():
    with pytest.raises(ValueError):
        PublicKey.from_bytes(bytes([0xC0]) + b"\x00" * 47)  # infinity pubkey
