"""Spec-test harness: fixture generation + directory runner round-trip.

Reference analog: spec-test-util's describeDirectorySpecTest consuming
the official layout (`beacon-node/test/spec/presets/*`). The generator
writes the same nesting; the runner must pass on valid cases, detect
tampered vectors, and honour expected-invalid (no `post`) semantics.
"""

import os

import pytest

from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.spec_test import (
    run_epoch_processing_suite,
    run_operations_suite,
    run_sanity_blocks_suite,
    run_sanity_slots_suite,
    run_shuffling_suite,
)
from lodestar_tpu.spec_test.fixtures import generate_suite_tree
from lodestar_tpu.types import get_types


@pytest.fixture(scope="module")
def tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("spec-tests")
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    paths = generate_suite_tree(str(root), fork_config, types, n_validators=16)
    # config with the generated genesis root for signed-object suites
    from lodestar_tpu.state_transition import interop_genesis_state

    state = interop_genesis_state(fork_config, types, 16, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    return paths, config, types


def test_sanity_blocks_suite_passes(tree):
    paths, config, types = tree
    result = run_sanity_blocks_suite(
        paths["sanity/blocks"], config, types, verify_signatures=False
    )
    assert result.ok(), result.failures
    assert result.total == 2  # valid 2-block case + expected-invalid case


def test_sanity_slots_suite_passes(tree):
    paths, config, types = tree
    result = run_sanity_slots_suite(paths["sanity/slots"], config, types)
    assert result.ok(), result.failures
    assert result.total == 2


def test_operations_suite_expected_invalid(tree):
    paths, config, types = tree
    result = run_operations_suite(
        paths["operations/voluntary_exit"], config, types, "voluntary_exit"
    )
    assert result.ok(), result.failures


def test_epoch_processing_suite_passes(tree):
    paths, config, types = tree
    result = run_epoch_processing_suite(
        paths["epoch_processing/justification_and_finalization"],
        config,
        types,
        "justification_and_finalization",
    )
    assert result.ok(), result.failures


def test_shuffling_suite_passes(tree):
    paths, config, types = tree
    result = run_shuffling_suite(paths["shuffling"], config)
    assert result.ok(), result.failures
    assert result.total == 3


def test_tampered_vector_detected(tree):
    """Corrupting a pinned post state must fail the case — the regression-
    pinning property the generated vectors exist for."""
    from lodestar_tpu import native

    paths, config, types = tree
    suite = paths["sanity/slots"]
    case_dir = os.path.join(suite, "slots_1")
    post_path = os.path.join(case_dir, "post.ssz_snappy")
    original = open(post_path, "rb").read()
    try:
        raw = bytearray(native.snappy_uncompress(original))
        raw[100] ^= 0xFF
        with open(post_path, "wb") as f:
            f.write(native.snappy_compress(bytes(raw)))
        result = run_sanity_slots_suite(suite, config, types)
        assert not result.ok()
        assert any("slots_1" in name for name, _ in result.failures)
    finally:
        with open(post_path, "wb") as f:
            f.write(original)


def test_runner_reports_totals(tree):
    paths, config, types = tree
    result = run_shuffling_suite(paths["shuffling"], config)
    assert result.total == result.passed == 3
    assert result.failures == []
