"""Fork-choice unit tests: proto-array head selection, votes, reorgs,
viability filtering, pruning — the behaviors the reference exercises via
`fork_choice` spec tests (on_block/on_attestation/on_tick steps) and
protoArray unit tests."""

import numpy as np
import pytest

from lodestar_tpu.fork_choice import ForkChoice, ForkChoiceStore, ProtoArray


def _root(i: int) -> bytes:
    return i.to_bytes(32, "big")


def make_fc(n_validators=8, balance=32):
    genesis = _root(0)
    proto = ProtoArray(justified_epoch=0, finalized_epoch=0)
    proto.on_block(0, genesis, None, b"\x00" * 32, 0, 0)
    store = ForkChoiceStore(
        current_slot=0,
        justified_checkpoint=(0, genesis),
        finalized_checkpoint=(0, genesis),
        justified_balances=np.full(n_validators, balance, np.int64),
    )
    return ForkChoice(store, proto, slots_per_epoch=8)


def test_chain_head_follows_blocks():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(2, _root(2), _root(1), b"", (0, _root(0)), (0, _root(0)))
    assert fc.update_head() == _root(2)


def test_votes_pick_heavier_fork():
    fc = make_fc()
    # fork at root 1: children 2 and 3
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(2, _root(2), _root(1), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(2, _root(3), _root(1), b"", (0, _root(0)), (0, _root(0)))
    fc.on_attestation([0, 1, 2], _root(2), 0)
    fc.on_attestation([3, 4], _root(3), 0)
    assert fc.update_head() == _root(2)
    # three more validators move to fork 3 → reorg
    fc.on_attestation([5, 6, 7], _root(3), 0)
    assert fc.update_head() == _root(3)


def test_vote_moves_subtract_old_weight():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(1, _root(2), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_attestation([0, 1, 2, 3, 4], _root(1), 0)
    assert fc.update_head() == _root(1)
    # same validators re-vote in a later epoch for the other fork
    fc.update_time(8)
    fc.on_attestation([0, 1, 2, 3, 4], _root(2), 1)
    assert fc.update_head() == _root(2)
    # old weights must have been fully removed
    idx1 = fc.proto.indices[_root(1)]
    assert fc.proto.weights[idx1] == 0


def test_equivocating_validators_removed():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(1, _root(2), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_attestation([0, 1, 2], _root(1), 0)
    fc.on_attestation([3, 4], _root(2), 0)
    assert fc.update_head() == _root(1)
    fc.on_attester_slashing([0, 1, 2])
    assert fc.update_head() == _root(2)


def test_stale_justification_filtered():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    # a block on a justified_epoch=1 branch; store moves to epoch 1
    fc.on_block(
        2, _root(2), _root(1), b"",
        (1, _root(1)), (0, _root(0)),
        justified_balances=np.full(8, 32, np.int64),
    )
    # head from the new justified root must land on the viable branch
    assert fc.update_head() == _root(2)


def test_future_epoch_attestation_queued():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(1, _root(2), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_attestation([0], _root(1), 0)
    fc.on_attestation([1, 2, 3], _root(2), 1)  # queued (epoch 1 > current 0)
    assert fc.update_head() == _root(1)
    fc.update_time(8)  # crossing into epoch 1 drains the queue
    assert fc.update_head() == _root(2)


def test_ancestor_and_descendant_queries():
    fc = make_fc()
    for i in range(1, 5):
        fc.on_block(i, _root(i), _root(i - 1), b"", (0, _root(0)), (0, _root(0)))
    assert fc.proto.is_descendant(_root(1), _root(4))
    assert not fc.proto.is_descendant(_root(4), _root(1))
    assert fc.get_ancestor(_root(4), 2) == _root(2)


def test_prune_keeps_post_finalized_tree():
    fc = make_fc()
    for i in range(1, 10):
        fc.on_block(i, _root(i), _root(i - 1), b"", (0, _root(0)), (0, _root(0)))
    fc.proto.prune_threshold = 2
    # epoch stays 0: the fabricated blocks carry (0,0) checkpoints, and
    # viability filtering compares node vs store epochs
    fc.store.finalized_checkpoint = (0, _root(5))
    fc.prune()
    assert _root(4) not in fc.proto.indices
    assert _root(5) in fc.proto.indices
    fc.store.justified_checkpoint = (0, _root(5))
    assert fc.update_head() == _root(9)
