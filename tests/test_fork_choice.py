"""Fork-choice unit tests: proto-array head selection, votes, reorgs,
viability filtering, pruning — the behaviors the reference exercises via
`fork_choice` spec tests (on_block/on_attestation/on_tick steps) and
protoArray unit tests."""

import numpy as np

from lodestar_tpu.fork_choice import ForkChoice, ForkChoiceStore, ProtoArray


def _root(i: int) -> bytes:
    return i.to_bytes(32, "big")


def make_fc(n_validators=8, balance=32, **kwargs):
    genesis = _root(0)
    proto = ProtoArray(justified_epoch=0, finalized_epoch=0)
    proto.on_block(0, genesis, None, b"\x00" * 32, 0, 0)
    store = ForkChoiceStore(
        current_slot=0,
        justified_checkpoint=(0, genesis),
        finalized_checkpoint=(0, genesis),
        justified_balances=np.full(n_validators, balance, np.int64),
    )
    return ForkChoice(store, proto, slots_per_epoch=8, **kwargs)


def test_chain_head_follows_blocks():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(2, _root(2), _root(1), b"", (0, _root(0)), (0, _root(0)))
    assert fc.update_head() == _root(2)


def test_votes_pick_heavier_fork():
    fc = make_fc()
    # fork at root 1: children 2 and 3
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(2, _root(2), _root(1), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(2, _root(3), _root(1), b"", (0, _root(0)), (0, _root(0)))
    fc.on_attestation([0, 1, 2], _root(2), 0)
    fc.on_attestation([3, 4], _root(3), 0)
    assert fc.update_head() == _root(2)
    # three more validators move to fork 3 → reorg
    fc.on_attestation([5, 6, 7], _root(3), 0)
    assert fc.update_head() == _root(3)


def test_vote_moves_subtract_old_weight():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(1, _root(2), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_attestation([0, 1, 2, 3, 4], _root(1), 0)
    assert fc.update_head() == _root(1)
    # same validators re-vote in a later epoch for the other fork
    fc.update_time(8)
    fc.on_attestation([0, 1, 2, 3, 4], _root(2), 1)
    assert fc.update_head() == _root(2)
    # old weights must have been fully removed
    idx1 = fc.proto.indices[_root(1)]
    assert fc.proto.weights[idx1] == 0


def test_equivocating_validators_removed():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(1, _root(2), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_attestation([0, 1, 2], _root(1), 0)
    fc.on_attestation([3, 4], _root(2), 0)
    assert fc.update_head() == _root(1)
    fc.on_attester_slashing([0, 1, 2])
    assert fc.update_head() == _root(2)


def test_stale_justification_filtered():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    # a block on a justified_epoch=1 branch; store moves to epoch 1
    fc.on_block(
        2, _root(2), _root(1), b"",
        (1, _root(1)), (0, _root(0)),
        justified_balances=np.full(8, 32, np.int64),
    )
    # head from the new justified root must land on the viable branch
    assert fc.update_head() == _root(2)


def test_future_epoch_attestation_queued():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(1, _root(2), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_attestation([0], _root(1), 0)
    fc.on_attestation([1, 2, 3], _root(2), 1)  # queued (epoch 1 > current 0)
    assert fc.update_head() == _root(1)
    fc.update_time(8)  # crossing into epoch 1 drains the queue
    assert fc.update_head() == _root(2)


def test_ancestor_and_descendant_queries():
    fc = make_fc()
    for i in range(1, 5):
        fc.on_block(i, _root(i), _root(i - 1), b"", (0, _root(0)), (0, _root(0)))
    assert fc.proto.is_descendant(_root(1), _root(4))
    assert not fc.proto.is_descendant(_root(4), _root(1))
    assert fc.get_ancestor(_root(4), 2) == _root(2)


def test_prune_keeps_post_finalized_tree():
    fc = make_fc()
    for i in range(1, 10):
        fc.on_block(i, _root(i), _root(i - 1), b"", (0, _root(0)), (0, _root(0)))
    fc.proto.prune_threshold = 2
    # epoch stays 0: the fabricated blocks carry (0,0) checkpoints, and
    # viability filtering compares node vs store epochs
    fc.store.finalized_checkpoint = (0, _root(5))
    fc.prune()
    assert _root(4) not in fc.proto.indices
    assert _root(5) in fc.proto.indices
    fc.store.justified_checkpoint = (0, _root(5))
    assert fc.update_head() == _root(9)


# -- proposer boost (reference forkChoice.ts:207-222, protoArray.ts:145-148) --

def test_proposer_boost_score_math():
    fc = make_fc(n_validators=8, balance=32)
    # committee weight per slot = total/SLOTS_PER_EPOCH = 8*32/8 = 32;
    # boost = 32 * 40 // 100 = 12 (reference computeProposerBoostScore)
    assert fc._compute_proposer_boost_score() == (8 * 32 // 8) * 40 // 100


def test_timely_block_gets_boost_and_wins_tie():
    fc = make_fc()
    fc.update_time(1)
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.on_block(1, _root(2), _root(0), b"", (0, _root(0)), (0, _root(0)))
    # equal votes on both forks
    fc.on_attestation([0, 1], _root(1), 0)
    fc.on_attestation([2, 3], _root(2), 0)
    fc.update_time(2)
    # timely block on fork 1 at the current slot: arrives 1s into slot 2
    fc.on_block(
        2, _root(3), _root(1), b"", (0, _root(0)), (0, _root(0)),
        block_delay_sec=1.0,
    )
    assert fc.proposer_boost_root == _root(3)
    assert fc.update_head() == _root(3)
    # the new tip carries exactly the boost (its ancestors carry the votes)
    idx = fc.proto.indices[_root(3)]
    assert fc.proto.weights[idx] == fc._compute_proposer_boost_score()
    # and the boosted subtree outweighs the other fork
    idx1 = fc.proto.indices[_root(1)]
    idx2 = fc.proto.indices[_root(2)]
    assert fc.proto.weights[idx1] > fc.proto.weights[idx2]


def test_late_block_gets_no_boost_and_boost_expires():
    fc = make_fc()
    fc.update_time(1)
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    # late arrival: 5s into a 12s slot (>= 12/3) — no boost
    fc.on_block(
        1, _root(2), _root(0), b"", (0, _root(0)), (0, _root(0)),
        block_delay_sec=5.0,
    )
    assert fc.proposer_boost_root is None
    # timely block this slot IS boosted, but the boost is backed out on
    # the next slot tick (previousProposerBoost accounting)
    fc.update_time(2)
    fc.on_block(
        2, _root(3), _root(1), b"", (0, _root(0)), (0, _root(0)),
        block_delay_sec=0.5,
    )
    fc.update_head()
    idx = fc.proto.indices[_root(3)]
    assert fc.proto.weights[idx] > 0
    fc.update_time(3)  # new slot: boost cleared
    assert fc.proposer_boost_root is None
    fc.update_head()
    assert fc.proto.weights[idx] == 0


def test_late_block_does_not_reorg_boosted_timely_head():
    """The attack proposer boost exists to stop: a late competing block for
    the same slot must not displace the boosted timely head when vote
    weight alone would tie (and WOULD win the byte tie-break)."""
    def run(boost_enabled):
        fc = make_fc(proposer_boost_enabled=boost_enabled)
        fc.update_time(1)
        fc.on_block(
            1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)),
            block_delay_sec=0.1,  # timely
        )
        fc.on_attestation([0], _root(1), 0)
        fc.on_block(
            1, _root(2), _root(0), b"", (0, _root(0)), (0, _root(0)),
            block_delay_sec=9.0,  # late
        )
        fc.on_attestation([1], _root(2), 0)
        return fc.update_head()

    # tied votes: without the boost the higher root bytes win the
    # tie-break (the late block); the boost keeps the timely head
    assert run(boost_enabled=False) == _root(2)
    assert run(boost_enabled=True) == _root(1)


# -- unrealized checkpoints (reference forkChoice.ts:406-453, onTick) --------

def test_unrealized_justification_pulls_up_at_epoch_boundary():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    # block says: if its epoch ended now, epoch 1 would be justified
    fc.update_time(9)  # slot 9 = epoch 1 (slots_per_epoch=8)
    fc.on_block(
        9, _root(2), _root(1), b"", (0, _root(0)), (0, _root(0)),
        unrealized_justified_checkpoint=(1, _root(1)),
        unrealized_finalized_checkpoint=(0, _root(0)),
    )
    assert fc.store.justified_checkpoint[0] == 0  # not yet realized
    assert fc.store.unrealized_justified == (1, _root(1))
    fc.update_time(16)  # epoch 2 boundary: pull up
    assert fc.store.justified_checkpoint == (1, _root(1))


def test_prior_epoch_block_pulls_up_immediately():
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.update_time(17)  # epoch 2
    # import a block FROM epoch 1 (past epoch) whose unrealized view
    # justifies epoch 1 — adopted right away (forkChoice.ts:445-453)
    fc.on_block(
        9, _root(2), _root(1), b"", (0, _root(0)), (0, _root(0)),
        unrealized_justified_checkpoint=(1, _root(1)),
        unrealized_finalized_checkpoint=(0, _root(0)),
    )
    assert fc.store.justified_checkpoint == (1, _root(1))


def test_prev_epoch_tip_viable_via_unrealized_checkpoints():
    """A tip from the previous epoch whose REALIZED justification lags but
    whose unrealized justification matches the store must stay viable
    (protoArray.ts:741-747)."""
    fc = make_fc()
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    fc.update_time(9)
    fc.on_block(
        9, _root(2), _root(1), b"", (0, _root(0)), (0, _root(0)),
        unrealized_justified_checkpoint=(1, _root(1)),
    )
    fc.update_time(16)  # pull-up realizes epoch-1 justification
    assert fc.store.justified_checkpoint[0] == 1
    # head walk from the justified root must still reach the tip whose
    # node.justified_epoch is 0 but unrealized is 1
    assert fc.update_head() == _root(2)


def test_bouncing_attack_guard_defers_late_justification():
    # minimal-preset-style window: only the first 2 slots of an epoch
    # accept an immediate justified-checkpoint bump
    fc = make_fc(safe_slots_to_update_justified=2)
    fc.on_block(1, _root(1), _root(0), b"", (0, _root(0)), (0, _root(0)))
    # move deep into an epoch (slot 14 = epoch 1 slot 6: past the window)
    fc.update_time(14)
    fc.on_block(
        14, _root(2), _root(1), b"", (1, _root(1)), (0, _root(0)),
    )
    # justification arrives late in the epoch: held in best_justified
    assert fc.store.justified_checkpoint[0] == 0
    assert fc.store.best_justified == (1, _root(1))
    fc.update_time(16)  # epoch boundary adopts it
    assert fc.store.justified_checkpoint == (1, _root(1))


def test_lvh_invalidation_marks_branch_invalid():
    """Engine INVALID + latestValidHash: blocks after the LVH and every
    descendant become non-viable; head selection moves to the valid fork
    (round-1 VERDICT: missing LVH invalidation path)."""
    fc = make_fc()
    # chain: 0 <- 1 <- 2 <- 3 (optimistic), with a competing 1 <- 4
    for slot, me, parent, status in [
        (1, 1, 0, "valid"),
        (2, 2, 1, "syncing"),
        (3, 3, 2, "syncing"),
        (2, 4, 1, "valid"),
    ]:
        fc.proto.on_block(
            slot, _root(me), _root(parent), b"", 0, 0, execution_status=status
        )
    fc.on_attestation([0, 1, 2], _root(3), 0)
    assert fc.update_head() == _root(3)
    # EL says block 3's payload chain is invalid back to block 1
    bad = fc.proto.invalidate_payloads(_root(3), _root(1))
    assert set(bad) == {_root(2), _root(3)}
    assert fc.proto.get_node(_root(2)).execution_status == "invalid"
    assert fc.proto.get_node(_root(1)).execution_status == "valid"
    # head walks to the surviving fork even though votes sat on 3
    assert fc.update_head() == _root(4)
    idx3 = fc.proto.indices[_root(3)]
    assert fc.proto.weights[idx3] == 0  # invalid weights zeroed


def test_lvh_invalidation_without_lvh_hits_only_head():
    fc = make_fc()
    fc.proto.on_block(1, _root(1), _root(0), b"", 0, 0, execution_status="syncing")
    fc.proto.on_block(2, _root(2), _root(1), b"", 0, 0, execution_status="syncing")
    bad = fc.proto.invalidate_payloads(_root(2), None)
    assert bad == [_root(2)]
    assert fc.proto.get_node(_root(1)).execution_status == "syncing"


def test_set_execution_valid_walks_ancestors():
    fc = make_fc()
    fc.proto.on_block(1, _root(1), _root(0), b"", 0, 0, execution_status="syncing")
    fc.proto.on_block(2, _root(2), _root(1), b"", 0, 0, execution_status="syncing")
    fc.proto.set_execution_valid(_root(2))
    assert fc.proto.get_node(_root(1)).execution_status == "valid"
    assert fc.proto.get_node(_root(2)).execution_status == "valid"
