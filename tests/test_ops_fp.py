"""Differential tests: JAX limb Fp (ops/fp.py) vs the big-int oracle."""

import random

import jax
import jax.numpy as jnp
import numpy as np

from lodestar_tpu.bls.fields import P
from lodestar_tpu.ops import fp
from lodestar_tpu.ops.limbs import (
    fp_from_mont_host,
    fp_to_mont_host,
    int_to_limbs,
    limbs_to_int,
)

rng = random.Random(1234)


def rand_fp() -> int:
    return rng.randrange(P)


def to_dev(xs: list[int]) -> jnp.ndarray:
    return jnp.asarray(np.stack([fp_to_mont_host(x) for x in xs]))


def from_dev(arr) -> list[int]:
    arr = np.asarray(arr)
    return [fp_from_mont_host(arr[i]) for i in range(arr.shape[0])]


def test_limb_roundtrip():
    for x in [0, 1, P - 1, rand_fp()]:
        assert limbs_to_int(int_to_limbs(x)) == x


def test_add_sub_neg():
    xs = [rand_fp() for _ in range(16)]
    ys = [rand_fp() for _ in range(16)]
    a, b = to_dev(xs), to_dev(ys)
    assert from_dev(jax.jit(fp.add)(a, b)) == [(x + y) % P for x, y in zip(xs, ys)]
    assert from_dev(jax.jit(fp.sub)(a, b)) == [(x - y) % P for x, y in zip(xs, ys)]
    assert from_dev(jax.jit(fp.neg)(a)) == [(-x) % P for x in xs]


def test_mul_square():
    xs = [0, 1, P - 1, P - 2] + [rand_fp() for _ in range(12)]
    ys = [P - 1, 0, P - 1, 2] + [rand_fp() for _ in range(12)]
    a, b = to_dev(xs), to_dev(ys)
    assert from_dev(jax.jit(fp.mul)(a, b)) == [(x * y) % P for x, y in zip(xs, ys)]
    assert from_dev(jax.jit(fp.square)(a)) == [(x * x) % P for x in xs]


def test_mont_roundtrip_device():
    xs = [0, 1, P - 1] + [rand_fp() for _ in range(5)]
    plain = jnp.asarray(np.stack([int_to_limbs(x) for x in xs]))
    m = jax.jit(fp.to_mont)(plain)
    back = jax.jit(fp.from_mont)(m)
    assert [limbs_to_int(np.asarray(back)[i]) for i in range(len(xs))] == xs


def test_inv_pow():
    xs = [1, 2, P - 1] + [rand_fp() for _ in range(5)]
    a = to_dev(xs)
    inv = jax.jit(fp.inv)(a)
    assert from_dev(inv) == [pow(x, P - 2, P) for x in xs]
    # a * a^-1 == 1
    prod = from_dev(fp.mul(a, inv))
    assert prod == [1] * len(xs)


def test_sqrt_candidate():
    from lodestar_tpu.bls.fields import Fq

    squares = [pow(rand_fp(), 2, P) for _ in range(4)]
    non_residue = next(x for x in range(2, 50) if not Fq(x).is_square())
    a = to_dev(squares + [non_residue])
    cand = from_dev(jax.jit(fp.sqrt_candidate)(a))
    for x, c in zip(squares, cand[:4]):
        assert (c * c) % P == x
    # non-residue: candidate squared must NOT give back the input
    assert (cand[4] * cand[4]) % P != non_residue


def test_predicates():
    xs = [0, 1, rand_fp()]
    a = to_dev(xs)
    assert np.asarray(fp.is_zero(a)).tolist() == [True, False, False]
    assert np.asarray(fp.eq(a, a)).tolist() == [True, True, True]


def test_lazy_reduction_invariant():
    # chain many ops; results must stay correct (values < 2p internally)
    x, y = rand_fp(), rand_fp()
    a, b = to_dev([x]), to_dev([y])
    acc, ref = a, x
    for _ in range(20):
        acc = fp.add(fp.mul(acc, b), a)
        ref = (ref * y + x) % P
    assert from_dev(acc) == [ref]


def test_vmap_consistency():
    xs = [rand_fp() for _ in range(8)]
    ys = [rand_fp() for _ in range(8)]
    a, b = to_dev(xs), to_dev(ys)
    direct = fp.mul(a, b)
    vmapped = jax.vmap(fp.mul)(a, b)
    assert np.array_equal(np.asarray(direct), np.asarray(vmapped))


def test_canonical_at_modulus_boundary():
    """Regression: values in [p, 2p) must canonicalize below p — the
    complement-add _cond_sub must fire exactly when a >= m (round-2 review
    caught canonical(p) == p with the stale plain-modulus argument)."""
    from lodestar_tpu.bls.fields import P
    from lodestar_tpu.ops.limbs import int_to_limbs, limbs_to_int

    for v in (P, P + 1, P + 12345, 2 * P - 1, P - 1, 0, 1):
        limbs = jnp.asarray(int_to_limbs(v))
        got = limbs_to_int(np.asarray(jax.jit(fp.canonical)(limbs)))
        assert got == v % P, f"canonical({v}) -> {got}"
    assert bool(jax.jit(fp.is_zero)(jnp.asarray(int_to_limbs(P))))
    assert not bool(jax.jit(fp.is_zero)(jnp.asarray(int_to_limbs(P - 1))))


def test_mul_all_impls_against_oracle():
    """Every multiply implementation — including the exact shipped TPU
    MXU/fused pipeline and both experimental carry variants (none of
    which are the shipped default — the scan multiply is, see
    fp._default_impl) — must match the big-int oracle."""
    from lodestar_tpu.ops import mxu_fp

    xs = [0, 1, P - 1, P - 2] + [rand_fp() for _ in range(8)]
    ys = [P - 1, 0, P - 1, 2] + [rand_fp() for _ in range(8)]
    a, b = to_dev(xs), to_dev(ys)
    ref = [(x * y) % P for x, y in zip(xs, ys)]
    assert from_dev(jax.jit(fp._mul_scan)(a, b)) == ref   # the default
    assert from_dev(jax.jit(fp._mul_fused)(a, b)) == ref  # MXU pipeline
    assert from_dev(jax.jit(mxu_fp.mul)(a, b)) == ref     # g/p-carry variant
    fused_ks = jax.jit(lambda x, y: fp._mul_fused(x, y, carry=fp.ks_carry))
    assert from_dev(fused_ks(a, b)) == ref                # signed-KS variant


def test_ks_carry_matches_carry_scan():
    """The experimental log-depth carry must agree with the scan reference
    on large positive columns and on signed columns (borrows)."""
    rng2 = random.Random(77)
    rows = []
    for _ in range(8):
        # big uncarried columns (like conv outputs): value stays < 2^768
        rows.append([rng2.randrange(0, 1 << 28) for _ in range(63)] + [0])
    for _ in range(8):
        # signed columns with borrows: x - y + 2^760 with x, y < 2^756
        x = rng2.randrange(1 << 756)
        y = rng2.randrange(1 << 756)
        cols = [((x >> (12 * k)) & 0xFFF) - ((y >> (12 * k)) & 0xFFF) for k in range(64)]
        cols[63] += 1 << (760 - 12 * 63)  # keep the value non-negative
        rows.append(cols)
    cols = np.asarray(rows, np.int32)
    got_ks = np.asarray(jax.jit(fp.ks_carry)(jnp.asarray(cols)))
    got_scan = np.asarray(jax.jit(fp.carry_scan)(jnp.asarray(cols)))
    assert np.array_equal(got_ks, got_scan)


def test_cyclotomic_square_matches_oracle():
    """Granger–Scott squaring == generic square on cyclotomic elements
    (the final exponentiation hard part runs entirely on these)."""
    from lodestar_tpu.bls import fields as f
    from lodestar_tpu.ops import fp12
    from lodestar_tpu.ops.io_host import fq12_to_limbs, limbs_to_fq12

    rng2 = random.Random(4)

    def rand_fq2():
        return f.Fq2(f.Fq(rng2.randrange(f.P)), f.Fq(rng2.randrange(f.P)))

    for _ in range(3):
        x = f.Fq12(
            f.Fq6(rand_fq2(), rand_fq2(), rand_fq2()),
            f.Fq6(rand_fq2(), rand_fq2(), rand_fq2()),
        )
        g = x.conjugate() * x.inverse()  # easy part: into the subgroup
        g = g.frobenius(2) * g
        limbs = fq12_to_limbs(g)
        got = limbs_to_fq12(np.asarray(jax.jit(fp12.cyclotomic_square)(limbs)))
        assert got == g * g


def test_lazy_fp2_with_nonreduced_representatives():
    """The lazy-reduction Fp2 product must be correct for inputs anywhere
    in the [0, 2p) contract, not just canonical < p values: fp.add's
    conditional 2p-reduction makes the integer p2 − p0 − p1 negative
    about half the time at the top of the range (the 8p² offset exists
    exactly for this — a canonical-only test cannot see the bug)."""
    import numpy as np

    from lodestar_tpu.bls.fields import P
    from lodestar_tpu.ops import fp2
    from lodestar_tpu.ops.limbs import R_MONT, int_to_limbs, limbs_to_int

    rng2 = np.random.default_rng(9)
    r_inv = pow(R_MONT, -1, P)
    for _ in range(10):
        a0 = P + int(rng2.integers(0, 2**60)) ** 6 % P
        a1 = P + int(rng2.integers(0, 2**60)) ** 6 % P
        b0 = P + int(rng2.integers(0, 2**60)) ** 6 % P
        b1 = int(rng2.integers(0, 2**60)) ** 6 % P
        a = jnp.asarray(np.stack([int_to_limbs(a0), int_to_limbs(a1)])[None])
        b = jnp.asarray(np.stack([int_to_limbs(b0), int_to_limbs(b1)])[None])
        out = np.asarray(fp2.mul(a, b))[0]
        c0, c1 = limbs_to_int(out[0]), limbs_to_int(out[1])
        assert c0 < 2 * P and c1 < 2 * P
        assert c0 % P == (a0 * b0 - a1 * b1) * r_inv % P
        assert c1 % P == (a0 * b1 + a1 * b0) * r_inv % P
        sq = np.asarray(fp2.square(a))[0]
        s0, s1 = limbs_to_int(sq[0]), limbs_to_int(sq[1])
        assert s0 < 2 * P and s1 < 2 * P
        assert s0 % P == (a0 * a0 - a1 * a1) * r_inv % P
        assert s1 % P == 2 * a0 * a1 * r_inv % P


def test_reduce_stack_per_sum_candidate_counts():
    """reduce_stack sizes its candidate scan PER SUM (round 6): a tight
    expression next to a loose one must still reduce correctly, and the
    total candidate count must be Σ k_j, not len(sums)·max k_j."""
    import numpy as np

    from lodestar_tpu.bls.fields import P
    from lodestar_tpu.ops.limbs import int_to_limbs, limbs_to_int

    rng2 = random.Random(77)
    for _ in range(5):
        a = rng2.randrange(2 * P)
        b = rng2.randrange(2 * P)
        c = rng2.randrange(2 * P)
        av = jnp.asarray(int_to_limbs(a))[None]
        bv = jnp.asarray(int_to_limbs(b))[None]
        cv = jnp.asarray(int_to_limbs(c))[None]
        W = fp.wrap
        # tight Sum (value < 4p, k=2) stacked with a loose one (8c − a,
        # lo = −1, hi = 8 → bias 1, k = 9) and a subtraction that goes
        # negative (needs its own bias, not the neighbor's)
        tight = W(av) + W(bv)
        loose = W(cv).double().double().double() - W(av)
        negy = W(av) - W(bv) - W(cv)
        outs = fp.reduce_stack([tight, loose, negy])
        got = [limbs_to_int(np.asarray(o)[0]) for o in outs]
        for g, expect in zip(
            got, [(a + b) % P, (8 * c - a) % P, (a - b - c) % P]
        ):
            assert g < 2 * P and g % P == expect
    # candidate accounting: the shared scan must carry Σ k_j rows — the
    # tight Sum's 2 + the loose one's 9 + the negative one's 4 — not
    # 3 sums × the loosest k (ADVICE r5: c0 rode its neighbor's k)
    seen = {}
    orig = fp._carry_scan_out

    def spy(t):
        seen["rows"] = t.shape[0]
        return orig(t)

    fp._carry_scan_out = spy
    try:
        W = fp.wrap
        fp.reduce_stack([
            W(av) + W(bv),                                  # hi 2 → k=2
            W(cv).double().double().double() - W(av),       # [-1, 8) → k=9
            W(av) - W(bv) - W(cv),                          # [-2, 1) → k=3
        ])
    finally:
        fp._carry_scan_out = orig
    assert seen["rows"] == 2 + 9 + 3
