"""Light-client e2e: an altair chain produces updates at import; a client
bootstraps from a trusted root and follows to the head verifying only
headers, merkle proofs, and sync signatures (reference: light-client
package unit + e2e; baseline config #4 — 32-pubkey aggregate verify)."""

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain import BeaconChain
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.light_client import Lightclient, LightClientError
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.state_transition.altair import upgrade_state_to_altair
from lodestar_tpu.types import get_types
from tests.test_altair import produce_altair_block, produce_attestations

N = 16
SPE = MINIMAL.SLOTS_PER_EPOCH


@pytest.fixture(scope="module")
def lc_chain():
    t = get_types(MINIMAL)
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    pre = interop_genesis_state(fork_config, t.phase0, N, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(pre.genesis_validators_root), MINIMAL
    )
    state = upgrade_state_to_altair(config, MINIMAL, pre, t.altair)
    chain = BeaconChain(config, t.altair, state)
    pending = []
    roots = []
    for slot in range(1, 3 * SPE + 1):
        chain.clock.set_slot(slot)
        signed = produce_altair_block(
            config, t.altair, chain.head_state, slot, pending
        )
        chain.process_block(signed, verify_signatures=False)
        roots.append(signed.message.hash_tree_root())
        pending = produce_attestations(
            config, t.altair, chain.head_state, roots[-1]
        )
    return config, t.altair, chain, roots


def test_server_produces_updates_and_bootstrap(lc_chain):
    config, types, chain, roots = lc_chain
    server = chain.light_client_server
    assert server.best_update_by_period  # at least period 0
    assert server.latest_optimistic_update is not None
    # bootstrap exists for attested (parent) blocks
    boot = server.get_bootstrap(roots[0])
    assert boot is not None
    assert len(boot.current_sync_committee_branch) == 5


def test_client_follows_chain(lc_chain):
    config, types, chain, roots = lc_chain
    server = chain.light_client_server
    client = Lightclient(config, types, MINIMAL)
    trusted = roots[0]
    client.bootstrap(trusted, server.get_bootstrap(trusted))
    assert client.finalized_header.slot == 1

    for period in sorted(server.best_update_by_period):
        client.process_update(server.best_update_by_period[period])
    # the best update carries the latest attested header of the period
    assert client.optimistic_header.slot > 1

    # optimistic fast path advances the head further
    client.process_optimistic_update(server.latest_optimistic_update)
    assert client.optimistic_header.slot == 3 * SPE - 1  # head's parent


def test_client_rejects_tampered_proofs(lc_chain):
    config, types, chain, roots = lc_chain
    server = chain.light_client_server
    client = Lightclient(config, types, MINIMAL)
    trusted = roots[0]

    # tampered bootstrap committee
    boot = server.get_bootstrap(trusted)
    bad_boot = types.LightClientBootstrap.deserialize(boot.serialize())
    bad_boot.current_sync_committee.pubkeys[0] = (
        bls.interop_secret_key(77).to_public_key().to_bytes()
    )
    with pytest.raises(LightClientError):
        client.bootstrap(trusted, bad_boot)

    client.bootstrap(trusted, boot)
    period = min(server.best_update_by_period)
    update = server.best_update_by_period[period]

    # tampered next-committee branch
    bad = types.LightClientUpdate.deserialize(update.serialize())
    bad.next_sync_committee_branch = [b"\x00" * 32] * 5
    with pytest.raises(LightClientError):
        client.process_update(bad)

    # tampered sync signature
    bad2 = types.LightClientUpdate.deserialize(update.serialize())
    bad2.sync_aggregate.sync_committee_signature = (
        bls.interop_secret_key(7).sign(b"x").to_bytes()
    )
    with pytest.raises(LightClientError):
        client.process_update(bad2)


def test_rest_follower_bootstraps_and_streams(lc_chain):
    """RestLightclientFollower: bootstrap + period catch-up over REST, then
    verified updates over the SSE stream (reference Lightclient.start +
    SSE subscribe, SURVEY §3.5)."""
    import threading

    from lodestar_tpu.api import BeaconApiServer
    from lodestar_tpu.api.client import BeaconApiClient
    from lodestar_tpu.api.impl import BeaconApiImpl
    from lodestar_tpu.chain.emitter import ChainEvent
    from lodestar_tpu.light_client.rest_follow import RestLightclientFollower

    config, types, chain, roots = lc_chain
    rest = BeaconApiServer(BeaconApiImpl(config, types, chain), port=0)
    rest.start()
    try:
        api = BeaconApiClient("127.0.0.1", rest.port)
        follower = RestLightclientFollower(
            config, types, MINIMAL, api, "127.0.0.1", rest.port
        )
        follower.start(roots[0])
        assert follower.lc.finalized_header.slot == 1
        assert follower.lc.optimistic_header.slot > 1

        # stream one optimistic update through SSE
        done = {}

        def run_follow():
            done["applied"] = follower.follow(max_events=1, timeout=10)

        t = threading.Thread(target=run_follow, daemon=True)
        t.start()
        import time

        time.sleep(0.3)
        chain.emitter.emit(
            ChainEvent.lightclient_optimistic_update,
            chain.light_client_server.latest_optimistic_update.to_obj(),
        )
        t.join(timeout=15)
        assert done.get("applied") == 1
        assert follower.lc.optimistic_header.slot == 3 * SPE - 1
    finally:
        rest.close()


def test_client_processes_finality_update(lc_chain):
    """process_finality_update advances the finalized header off a
    verified finality proof (reference processFinalizedUpdate)."""
    config, types, chain, roots = lc_chain
    server = chain.light_client_server
    client = Lightclient(config, types, MINIMAL)
    client.bootstrap(roots[0], server.get_bootstrap(roots[0]))

    fin_update = getattr(server, "latest_finality_update", None)
    if fin_update is None:
        # synthesize from the best period update (same proof structure)
        best = server.best_update_by_period[max(server.best_update_by_period)]
        if not any(bytes(b) != b"\x00" * 32 for b in best.finality_branch):
            import pytest

            pytest.skip("fixture chain has no finalized checkpoint yet")
        fin_update = types.LightClientFinalityUpdate(
            attested_header=best.attested_header.copy(),
            finalized_header=best.finalized_header.copy(),
            finality_branch=[bytes(b) for b in best.finality_branch],
            sync_aggregate=best.sync_aggregate.copy(),
            signature_slot=best.signature_slot,
        )
    before = int(client.finalized_header.slot)
    client.process_finality_update(fin_update)
    assert int(client.finalized_header.slot) >= before
    # a tampered proof must be rejected
    bad = types.LightClientFinalityUpdate.deserialize(fin_update.serialize())
    bad.finalized_header.state_root = b"\xff" * 32
    import pytest as _pytest

    from lodestar_tpu.light_client.client import LightClientError

    client2 = Lightclient(config, types, MINIMAL)
    client2.bootstrap(roots[0], server.get_bootstrap(roots[0]))
    if int(bad.finalized_header.slot) > int(client2.finalized_header.slot):
        with _pytest.raises(LightClientError):
            client2.process_finality_update(bad)
