"""Light-client e2e: an altair chain produces updates at import; a client
bootstraps from a trusted root and follows to the head verifying only
headers, merkle proofs, and sync signatures (reference: light-client
package unit + e2e; baseline config #4 — 32-pubkey aggregate verify)."""

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain import BeaconChain
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.light_client import Lightclient, LightClientError
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.state_transition.altair import upgrade_state_to_altair
from lodestar_tpu.types import get_types
from tests.test_altair import produce_altair_block, produce_attestations

N = 16
SPE = MINIMAL.SLOTS_PER_EPOCH


@pytest.fixture(scope="module")
def lc_chain():
    t = get_types(MINIMAL)
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    pre = interop_genesis_state(fork_config, t.phase0, N, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(pre.genesis_validators_root), MINIMAL
    )
    state = upgrade_state_to_altair(config, MINIMAL, pre, t.altair)
    chain = BeaconChain(config, t.altair, state)
    pending = []
    roots = []
    for slot in range(1, 3 * SPE + 1):
        chain.clock.set_slot(slot)
        signed = produce_altair_block(
            config, t.altair, chain.head_state, slot, pending
        )
        chain.process_block(signed, verify_signatures=False)
        roots.append(signed.message.hash_tree_root())
        pending = produce_attestations(
            config, t.altair, chain.head_state, roots[-1]
        )
    return config, t.altair, chain, roots


def test_server_produces_updates_and_bootstrap(lc_chain):
    config, types, chain, roots = lc_chain
    server = chain.light_client_server
    assert server.best_update_by_period  # at least period 0
    assert server.latest_optimistic_update is not None
    # bootstrap exists for attested (parent) blocks
    boot = server.get_bootstrap(roots[0])
    assert boot is not None
    assert len(boot.current_sync_committee_branch) == 5


def test_client_follows_chain(lc_chain):
    config, types, chain, roots = lc_chain
    server = chain.light_client_server
    client = Lightclient(config, types, MINIMAL)
    trusted = roots[0]
    client.bootstrap(trusted, server.get_bootstrap(trusted))
    assert client.finalized_header.slot == 1

    for period in sorted(server.best_update_by_period):
        client.process_update(server.best_update_by_period[period])
    # the best update carries the latest attested header of the period
    assert client.optimistic_header.slot > 1

    # optimistic fast path advances the head further
    client.process_optimistic_update(server.latest_optimistic_update)
    assert client.optimistic_header.slot == 3 * SPE - 1  # head's parent


def test_client_rejects_tampered_proofs(lc_chain):
    config, types, chain, roots = lc_chain
    server = chain.light_client_server
    client = Lightclient(config, types, MINIMAL)
    trusted = roots[0]

    # tampered bootstrap committee
    boot = server.get_bootstrap(trusted)
    bad_boot = types.LightClientBootstrap.deserialize(boot.serialize())
    bad_boot.current_sync_committee.pubkeys[0] = (
        bls.interop_secret_key(77).to_public_key().to_bytes()
    )
    with pytest.raises(LightClientError):
        client.bootstrap(trusted, bad_boot)

    client.bootstrap(trusted, boot)
    period = min(server.best_update_by_period)
    update = server.best_update_by_period[period]

    # tampered next-committee branch
    bad = types.LightClientUpdate.deserialize(update.serialize())
    bad.next_sync_committee_branch = [b"\x00" * 32] * 5
    with pytest.raises(LightClientError):
        client.process_update(bad)

    # tampered sync signature
    bad2 = types.LightClientUpdate.deserialize(update.serialize())
    bad2.sync_aggregate.sync_committee_signature = (
        bls.interop_secret_key(7).sign(b"x").to_bytes()
    )
    with pytest.raises(LightClientError):
        client.process_update(bad2)
