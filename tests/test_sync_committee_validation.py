"""Sync-committee gossip validation ladders (VERDICT round-1 missing #3).

Reference: chain/validation/syncCommittee.ts:1-80 (message ladder) and
syncCommitteeContributionAndProof.ts (contribution ladder). These are
live-chain tests in the style of test_network_gossip.py: real minimal-preset
altair chain, real BLS signatures, invalid variants must be REJECTed and
duplicates IGNOREd.
"""

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain import BeaconChain
from lodestar_tpu.chain.validation import (
    GossipAction,
    _sync_subcommittee_members,
    is_sync_committee_aggregator,
    validate_gossip_sync_committee,
    validate_gossip_sync_contribution_and_proof,
)
from lodestar_tpu.config.beacon_config import (
    BeaconConfig,
    ChainForkConfig,
    compute_signing_root,
)
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.params import (
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
)
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.state_transition.altair import upgrade_state_to_altair
from lodestar_tpu.types import get_types

SPE = MINIMAL.SLOTS_PER_EPOCH
SUBNET_SIZE = MINIMAL.SYNC_COMMITTEE_SUBNET_SIZE


def _sk(i):
    return bls.interop_secret_key(i)


@pytest.fixture(scope="module")
def chain_setup():
    t = get_types(MINIMAL)
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    pre = interop_genesis_state(fork_config, t.phase0, 16, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(pre.genesis_validators_root), MINIMAL
    )
    state = upgrade_state_to_altair(config, MINIMAL, pre, t.altair)
    chain = BeaconChain(config, t.altair, state)
    chain.clock.set_slot(1)
    # move 1s INTO slot 1: at the exact boundary the previous slot is
    # still current within MAXIMUM_GOSSIP_CLOCK_DISPARITY
    chain.clock._now += 1.0
    return config, t.altair, chain


def _make_message(config, chain, subnet=0, position=0, flip_sig=False, slot=1):
    members = _sync_subcommittee_members(chain, subnet)
    validator_index = members[position]
    domain = config.get_domain(DOMAIN_SYNC_COMMITTEE, slot, slot // SPE)
    root = compute_signing_root(chain.head_root, domain)
    sk = _sk(validator_index + (99 if flip_sig else 0))
    types = get_types(MINIMAL).altair
    return types.SyncCommitteeMessage(
        slot=slot,
        beacon_block_root=chain.head_root,
        validator_index=validator_index,
        signature=sk.sign(root).to_bytes(),
    )


def test_message_accept_then_duplicate_ignore(chain_setup):
    config, types, chain = chain_setup
    msg = _make_message(config, chain, subnet=0, position=0)
    res = validate_gossip_sync_committee(chain, types, msg, 0)
    assert res.action == GossipAction.ACCEPT, res.reason
    assert res.attesting_index == 0  # position in the subcommittee
    # identical second delivery: IGNORE (seen cache)
    res2 = validate_gossip_sync_committee(chain, types, msg, 0)
    assert res2.action == GossipAction.IGNORE


def test_message_bad_signature_rejected(chain_setup):
    config, types, chain = chain_setup
    msg = _make_message(config, chain, subnet=0, position=1, flip_sig=True)
    res = validate_gossip_sync_committee(chain, types, msg, 0)
    assert res.action == GossipAction.REJECT
    assert "signature" in res.reason


def test_message_wrong_subcommittee_rejected(chain_setup):
    config, types, chain = chain_setup
    members0 = _sync_subcommittee_members(chain, 0)
    # find a subnet whose membership differs for this validator
    target = None
    for subnet in range(1, 4):
        if members0[2] not in _sync_subcommittee_members(chain, subnet):
            target = subnet
            break
    if target is None:
        pytest.skip("validator sits in every subcommittee in this tiny state")
    msg = _make_message(config, chain, subnet=0, position=2)
    res = validate_gossip_sync_committee(chain, types, msg, target)
    assert res.action == GossipAction.REJECT
    assert "subcommittee" in res.reason


def test_message_out_of_range_subnet_and_wrong_slot(chain_setup):
    config, types, chain = chain_setup
    msg = _make_message(config, chain, subnet=0, position=3)
    assert (
        validate_gossip_sync_committee(chain, types, msg, 7).action
        == GossipAction.REJECT
    )
    stale = _make_message(config, chain, subnet=0, position=3, slot=0)
    assert (
        validate_gossip_sync_committee(chain, types, stale, 0).action
        == GossipAction.IGNORE
    )


def _make_contribution(
    config, chain, subnet=0, agg_position=0, n_participants=3, slot=1,
    flip_aggregate=False, flip_envelope=False,
):
    types = get_types(MINIMAL).altair
    members = _sync_subcommittee_members(chain, subnet)
    aggregator_index = members[agg_position]

    # participants sign the head root
    domain = config.get_domain(DOMAIN_SYNC_COMMITTEE, slot, slot // SPE)
    root = compute_signing_root(chain.head_root, domain)
    bits = [False] * SUBNET_SIZE
    sigs = []
    for pos in range(n_participants):
        bits[pos] = True
        sigs.append(_sk(members[pos] + (99 if flip_aggregate else 0)).sign(root))
    aggregate = (
        bls.aggregate_signatures(sigs).to_bytes() if sigs else b"\xc0" + b"\x00" * 95
    )
    contribution = types.SyncCommitteeContribution(
        slot=slot,
        beacon_block_root=chain.head_root,
        subcommittee_index=subnet,
        aggregation_bits=bits,
        signature=aggregate,
    )

    sel_domain = config.get_domain(
        DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, slot, slot // SPE
    )
    sel_data = types.SyncAggregatorSelectionData(slot=slot, subcommittee_index=subnet)
    proof = _sk(aggregator_index).sign(
        compute_signing_root(sel_data.hash_tree_root(), sel_domain)
    ).to_bytes()

    cap = types.ContributionAndProof(
        aggregator_index=aggregator_index,
        contribution=contribution,
        selection_proof=proof,
    )
    env_domain = config.get_domain(DOMAIN_CONTRIBUTION_AND_PROOF, slot, slot // SPE)
    env_signer = aggregator_index + (99 if flip_envelope else 0)
    env_sig = _sk(env_signer).sign(
        compute_signing_root(cap.hash_tree_root(), env_domain)
    ).to_bytes()
    return types.SignedContributionAndProof(message=cap, signature=env_sig)


def test_contribution_accept_then_dedup(chain_setup):
    config, types, chain = chain_setup
    # minimal preset: subcommittee size 8 // TARGET 16 → modulo 1, every
    # selection proof selects (keeps the aggregator gate testable)
    signed = _make_contribution(config, chain, subnet=1, n_participants=3)
    assert is_sync_committee_aggregator(
        signed.message.selection_proof, chain.preset
    )
    res = validate_gossip_sync_contribution_and_proof(chain, types, signed)
    assert res.action == GossipAction.ACCEPT, res.reason
    # same aggregator again (fewer participants → not a superset IGNORE,
    # but the aggregator-known IGNORE)
    fewer = _make_contribution(config, chain, subnet=1, n_participants=2)
    res2 = validate_gossip_sync_contribution_and_proof(chain, types, fewer)
    assert res2.action == GossipAction.IGNORE
    # different aggregator, subset participants → superset IGNORE
    subset = _make_contribution(
        config, chain, subnet=1, agg_position=4, n_participants=2
    )
    res3 = validate_gossip_sync_contribution_and_proof(chain, types, subset)
    assert res3.action == GossipAction.IGNORE
    assert "participants" in res3.reason


def test_contribution_bad_signatures_rejected(chain_setup):
    config, types, chain = chain_setup
    bad_agg = _make_contribution(
        config, chain, subnet=2, n_participants=2, flip_aggregate=True
    )
    res = validate_gossip_sync_contribution_and_proof(chain, types, bad_agg)
    assert res.action == GossipAction.REJECT
    assert "signature" in res.reason

    bad_env = _make_contribution(
        config, chain, subnet=2, agg_position=5, n_participants=2,
        flip_envelope=True,
    )
    res2 = validate_gossip_sync_contribution_and_proof(chain, types, bad_env)
    assert res2.action == GossipAction.REJECT


def test_contribution_no_participants_rejected(chain_setup):
    config, types, chain = chain_setup
    signed = _make_contribution(config, chain, subnet=3, n_participants=0)
    res = validate_gossip_sync_contribution_and_proof(chain, types, signed)
    assert res.action == GossipAction.REJECT
    assert "participants" in res.reason


def test_contribution_out_of_range_subcommittee(chain_setup):
    config, types, chain = chain_setup
    signed = _make_contribution(config, chain, subnet=0, agg_position=6)
    signed.message.contribution.subcommittee_index = 9
    res = validate_gossip_sync_contribution_and_proof(chain, types, signed)
    assert res.action == GossipAction.REJECT


def test_duplicate_positions_all_reported():
    """Sync committees sample with replacement: one validator can hold
    several positions of a subcommittee, and its single (deduped) message
    must carry every position so the pool sets all its bits.

    Deterministic setup (VERDICT r3 weak #7): 6 validators < 8 positions
    per subcommittee, so the pigeonhole principle guarantees a duplicated
    member in EVERY subnet — no sampling luck, no skip."""
    t = get_types(MINIMAL)
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    pre = interop_genesis_state(
        fork_config, t.phase0, 6, genesis_time=1_600_000_000
    )
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(pre.genesis_validators_root), MINIMAL
    )
    state = upgrade_state_to_altair(config, MINIMAL, pre, t.altair)
    chain = BeaconChain(config, t.altair, state)
    chain.clock.set_slot(1)
    chain.clock._now += 1.0
    types = t.altair
    from lodestar_tpu.chain.validation import _sync_subcommittee_members

    found = None
    for subnet in range(4):
        members = _sync_subcommittee_members(chain, subnet)
        for v in members:
            if members.count(v) > 1:
                found = (subnet, v, [i for i, x in enumerate(members) if x == v])
                break
        if found:
            break
    assert found is not None, "pigeonhole guarantees a duplicate with 6 validators"
    subnet, validator, positions = found
    pos0 = positions[0]
    msg = _make_message(config, chain, subnet=subnet, position=pos0)
    chain.seen_sync_committee._seen.discard((1, subnet, validator))
    res = validate_gossip_sync_committee(chain, types, msg, subnet)
    assert res.action == GossipAction.ACCEPT, res.reason
    assert res.positions == positions
