"""SSZ engine tests (reference analog: @chainsafe/ssz test suite +
ssz_generic spec-test categories)."""

import pytest

from lodestar_tpu.config import compute_fork_data_root
from lodestar_tpu.ssz import (
    BitListType,
    BitVectorType,
    Bytes4,
    Bytes32,
    ByteListType,
    Container,
    DeserializationError,
    ListType,
    UnionType,
    VectorType,
    ZERO_HASHES,
    boolean,
    hash_pair,
    merkleize_chunks,
    uint8,
    uint16,
    uint64,
    uint256,
)


def test_uint_roundtrip_and_root():
    assert uint64.serialize(1) == b"\x01" + b"\x00" * 7
    assert uint64.deserialize(uint64.serialize(2**64 - 1)) == 2**64 - 1
    assert uint64.hash_tree_root(0) == b"\x00" * 32
    assert uint16.serialize(0x0102) == b"\x02\x01"
    with pytest.raises(ValueError):
        uint8.serialize(256)
    with pytest.raises(DeserializationError):
        uint64.deserialize(b"\x00" * 7)


def test_boolean():
    assert boolean.serialize(True) == b"\x01"
    with pytest.raises(DeserializationError):
        boolean.deserialize(b"\x02")


def test_vector_uint_packing():
    v = VectorType(uint64, 2)
    # Two uint64s pack into a single 32-byte chunk -> root == padded chunk
    root = v.hash_tree_root([1, 2])
    expected = (1).to_bytes(8, "little") + (2).to_bytes(8, "little") + b"\x00" * 16
    assert root == expected
    assert v.deserialize(v.serialize([1, 2])) == [1, 2]
    with pytest.raises(ValueError):
        v.serialize([1])


def test_list_mixin_length():
    t = ListType(uint64, 1024)
    # empty list: root = mix_in_length(zero-subtree root, 0)
    depth = 8  # 1024 uint64 = 256 chunks -> depth 8
    assert t.hash_tree_root([]) == hash_pair(ZERO_HASHES[depth], (0).to_bytes(32, "little"))
    vals = list(range(100))
    assert t.deserialize(t.serialize(vals)) == vals


def test_bitvector():
    t = BitVectorType(10)
    bits = [True, False] * 5
    data = t.serialize(bits)
    assert len(data) == 2
    assert t.deserialize(data) == bits
    # nonzero padding must be rejected
    with pytest.raises(DeserializationError):
        t.deserialize(b"\xff\xff")


def test_bitlist_delimiter():
    t = BitListType(8)
    bits = [True, True, False, True, False, True, False, False]
    assert t.serialize(bits) == bytes([0x2B, 0x01])
    assert t.deserialize(bytes([0x2B, 0x01])) == bits
    assert t.serialize([]) == b"\x01"
    assert t.deserialize(b"\x01") == []
    with pytest.raises(DeserializationError):
        t.deserialize(b"\x00")  # no delimiter
    with pytest.raises(DeserializationError):
        t.deserialize(b"")
    with pytest.raises(DeserializationError):
        t.deserialize(bytes([0x2B, 0x01, 0x00]))  # excess bytes
    # bitlist root differs from bitvector root (length mix-in)
    assert t.hash_tree_root(bits) != BitVectorType(8).hash_tree_root(bits)


def test_bytelist_limits():
    t = ByteListType(10)
    assert t.deserialize(t.serialize(b"hello")) == b"hello"
    with pytest.raises(ValueError):
        t.serialize(b"x" * 11)


class ForkData(Container):
    fields = [("current_version", Bytes4), ("genesis_validators_root", Bytes32)]


def test_container_fork_data_matches_config_handroll():
    """The config layer hand-rolls ForkData's root (beacon_config.py) — the
    generic SSZ container must agree."""
    version = bytes.fromhex("01000000")
    gvr = b"\x42" * 32
    fd = ForkData(current_version=version, genesis_validators_root=gvr)
    assert fd.hash_tree_root() == compute_fork_data_root(version, gvr)
    assert fd.serialize() == version + gvr
    assert ForkData.deserialize(fd.serialize()) == fd


class Inner(Container):
    fields = [("a", uint64), ("data", ByteListType(64))]


class Outer(Container):
    fields = [
        ("x", uint16),
        ("inner", Inner.ssz_type),
        ("items", ListType(uint64, 32)),
        ("fixed", Bytes4),
    ]


def test_variable_size_container_roundtrip():
    o = Outer(
        x=7,
        inner=Inner(a=9, data=b"\xaa\xbb"),
        items=[1, 2, 3],
        fixed=b"\x01\x02\x03\x04",
    )
    data = o.serialize()
    o2 = Outer.deserialize(data)
    assert o2 == o
    assert o2.inner.data == b"\xaa\xbb"
    # fixed part: 2 (x) + 4 (offset inner) + 4 (offset items) + 4 (fixed) = 14
    assert int.from_bytes(data[2:6], "little") == 14
    # tamper with first offset -> rejected
    bad = bytearray(data)
    bad[2] = 13
    with pytest.raises(DeserializationError):
        Outer.deserialize(bytes(bad))


def test_container_copy_is_deep():
    o = Outer(x=1, inner=Inner(a=2, data=b"z"), items=[5], fixed=b"\x00" * 4)
    c = o.copy()
    c.inner.a = 99
    c.items.append(6)
    assert o.inner.a == 2
    assert o.items == [5]


def test_list_of_containers():
    t = ListType(Inner.ssz_type, 4)
    vals = [Inner(a=1, data=b"x"), Inner(a=2, data=b"yy")]
    out = t.deserialize(t.serialize(vals))
    assert out == vals
    # root = mix_in_length(merkleize([htr(e)...], limit=4), 2)
    roots = b"".join(v.hash_tree_root() for v in vals)
    assert t.hash_tree_root(vals) == hash_pair(
        merkleize_chunks(roots, limit=4), (2).to_bytes(32, "little")
    )


def test_union():
    t = UnionType([None, uint64])
    assert t.deserialize(t.serialize((1, 5))) == (1, 5)
    assert t.deserialize(t.serialize((0, None))) == (0, None)
    with pytest.raises(DeserializationError):
        t.deserialize(b"\x05")


def test_uint256():
    v = 2**255 - 19
    assert uint256.deserialize(uint256.serialize(v)) == v
    assert uint256.hash_tree_root(v) == v.to_bytes(32, "little")


def test_merkleize_virtual_padding_scales():
    # limit 2**40 (validator registry) must not materialize chunks
    root = merkleize_chunks(b"\x11" * 32, limit=2**40)
    assert len(root) == 32
    # equals hashing up 40 levels with zero siblings
    acc = b"\x11" * 32
    for d in range(40):
        acc = hash_pair(acc, ZERO_HASHES[d])
    assert root == acc


def test_list_varsize_rejects_zero_first_offset():
    # regression: first offset 0 must not be read as "empty list"
    t = ListType(ByteListType(100), 10)
    with pytest.raises(DeserializationError):
        t.deserialize(b"\x00\x00\x00\x00\xff\xff\xff")


def test_union_none_only_first_option():
    with pytest.raises(TypeError):
        UnionType([uint64, None])
    with pytest.raises(TypeError):
        UnionType([None])
