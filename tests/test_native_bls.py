"""Differential tests: native C BLS12-381 tier vs the big-int oracle.

The native tier (native/src/bls12.c) must agree bit-for-bit with
lodestar_tpu.bls on decompression, subgroup checks, hash-to-curve and
aggregation — it feeds the device verifier, so a mismatch is a consensus
fault. Reference analog: blst preprocessing at multithread/worker.ts:33-55.
"""

import numpy as np
import pytest

from lodestar_tpu import native
from lodestar_tpu.bls import api as bls
from lodestar_tpu.bls.curve import g2_to_bytes
from lodestar_tpu.bls.hash_to_curve import DST_G2, hash_to_g2
from lodestar_tpu.ops.io_host import g1_affine_to_limbs, g2_affine_to_limbs

pytestmark = pytest.mark.skipif(
    not native.HAVE_NATIVE_BLS, reason="native BLS extension unavailable"
)


def test_g1_decompress_matches_oracle():
    for i in range(6):
        pk = bls.interop_secret_key(i).to_public_key()
        rc, limbs = native.bls_g1_decompress(pk.to_bytes())
        assert rc == 0
        ox, oy, _ = g1_affine_to_limbs(pk.point)
        np.testing.assert_array_equal(limbs[0], ox)
        np.testing.assert_array_equal(limbs[1], oy)


def test_g2_decompress_matches_oracle():
    for i in range(4):
        sig = bls.interop_secret_key(i).sign(bytes([i]) * 32)
        rc, limbs = native.bls_g2_decompress(sig.to_bytes())
        assert rc == 0
        ox, oy, _ = g2_affine_to_limbs(sig.point)
        np.testing.assert_array_equal(limbs[0], ox)
        np.testing.assert_array_equal(limbs[1], oy)


def test_hash_to_g2_matches_oracle():
    for msg in (b"", b"abc", b"\x00" * 32, b"\xff" * 32, b"lodestar-tpu"):
        rc, limbs = native.bls_hash_to_g2(msg, DST_G2)
        assert rc == 0
        p = hash_to_g2(msg)
        ox, oy, _ = g2_affine_to_limbs(p)
        np.testing.assert_array_equal(limbs[0], ox)
        np.testing.assert_array_equal(limbs[1], oy)


def test_g1_aggregate_matches_oracle():
    pks = [bls.interop_secret_key(i).to_public_key() for i in range(7)]
    agg = bls.aggregate_pubkeys(pks)
    rc, limbs = native.bls_g1_aggregate(b"".join(p.to_bytes() for p in pks))
    assert rc == 0
    ox, oy, _ = g1_affine_to_limbs(agg.point)
    np.testing.assert_array_equal(limbs[0], ox)
    np.testing.assert_array_equal(limbs[1], oy)


def test_infinity_encodings():
    rc, _ = native.bls_g1_decompress(bytes([0xC0]) + b"\x00" * 47)
    assert rc == 1
    rc, _ = native.bls_g2_decompress(bytes([0xC0]) + b"\x00" * 95)
    assert rc == 1
    # malformed infinity (stray bits)
    rc, _ = native.bls_g1_decompress(bytes([0xC0]) + b"\x00" * 46 + b"\x01")
    assert rc == -1


def test_malformed_rejected():
    # no compression flag
    rc, _ = native.bls_g1_decompress(b"\x00" * 48)
    assert rc == -1
    # x >= p
    rc, _ = native.bls_g1_decompress(bytes([0x9F]) + b"\xff" * 47)
    assert rc == -1
    # x not on curve: flip bits until decompression fails with -2
    pk = bls.interop_secret_key(0).to_public_key().to_bytes()
    found = False
    for delta in range(1, 40):
        cand = bytearray(pk)
        cand[-1] = (cand[-1] + delta) & 0xFF
        rc, _ = native.bls_g1_decompress(bytes(cand))
        if rc == -2:
            found = True
            break
    assert found, "expected an off-curve x nearby"


def test_subgroup_check_rejects_low_order_mul():
    """A point on the curve but outside G2 must fail with -3."""
    # construct an E2 point not in G2: take hash output before cofactor
    # clearing — overwhelmingly likely outside the subgroup.
    from lodestar_tpu.bls.hash_to_curve import hash_to_field_fq2, map_to_curve_g2

    u0, u1 = hash_to_field_fq2(b"subgroup-test", 2)
    q = map_to_curve_g2(u0) + map_to_curve_g2(u1)
    assert not q.is_in_subgroup()
    raw = g2_to_bytes(q)
    rc, _ = native.bls_g2_decompress(raw, True)
    assert rc == -3
    rc, _ = native.bls_g2_decompress(raw, False)
    assert rc == 0


def test_marshal_sets_roundtrip_and_flags():
    n = 4
    pks, msgs, sigs = b"", b"", b""
    for i in range(n):
        sk = bls.interop_secret_key(i)
        m = bytes([i]) * 32
        pks += sk.to_public_key().to_bytes()
        msgs += m
        sigs += sk.sign(m).to_bytes()
    pk_x, pk_y, msg_x, msg_y, sig_x, sig_y, ok = native.bls_marshal_sets(
        pks, msgs, sigs, DST_G2
    )
    assert ok.all()
    # spot-check lane 2 against the oracle
    sk = bls.interop_secret_key(2)
    ox, oy, _ = g1_affine_to_limbs(sk.to_public_key().point)
    np.testing.assert_array_equal(pk_x[2], ox)
    hx, hy, _ = g2_affine_to_limbs(hash_to_g2(bytes([2]) * 32))
    np.testing.assert_array_equal(msg_x[2], hx)
    np.testing.assert_array_equal(msg_y[2], hy)

    # corrupt one signature -> only that lane flagged
    bad = bytearray(sigs)
    bad[96 * 1] = 0x00  # kill the compression flag of set 1
    _, _, _, _, _, _, ok2 = native.bls_marshal_sets(pks, msgs, bytes(bad), DST_G2)
    assert not ok2[1] and ok2[0] and ok2[2] and ok2[3]

    # infinity pubkey -> invalid lane
    bad_pks = bytearray(pks)
    bad_pks[0:48] = bytes([0xC0]) + b"\x00" * 47
    _, _, _, _, _, _, ok3 = native.bls_marshal_sets(bytes(bad_pks), msgs, sigs, DST_G2)
    assert not ok3[0] and ok3[1]


def test_verifier_native_marshal_agrees_with_oracle_marshal():
    """TpuBlsVerifier._marshal must produce identical arrays through the
    native fast path and the big-int fallback."""
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    sets = []
    for i in range(3):
        sk = bls.interop_secret_key(i)
        m = bytes([7 + i]) * 32
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(), message=m, signature=sk.sign(m).to_bytes()
            )
        )
    v = TpuBlsVerifier(buckets=(4,))
    arrs = v._marshal(sets)
    assert arrs is not None and arrs.n == 3 and arrs.valid[:3].all()
    for i, s in enumerate(sets):
        ox, oy, _ = g1_affine_to_limbs(s.pubkey.point)
        np.testing.assert_array_equal(arrs.pk_x[i], ox)
        hx, hy, _ = g2_affine_to_limbs(hash_to_g2(s.message))
        np.testing.assert_array_equal(arrs.msg_x[i], hx)
        np.testing.assert_array_equal(arrs.msg_y[i], hy)
        sx, sy, _ = g2_affine_to_limbs(bls.Signature.from_bytes(s.signature).point)
        np.testing.assert_array_equal(arrs.sig_x[i], sx)
        np.testing.assert_array_equal(arrs.sig_y[i], sy)


def test_fast_subgroup_checks_reject_nonmembers():
    """The endomorphism membership tests (G1: phi(P) + [x^2]P == O;
    G2: psi(P) + [|x|]P == O) must reject on-curve points OUTSIDE the
    subgroups — completeness, not just soundness. Vectors generated from
    the Python oracle (curve points whose order-multiples are not
    infinity)."""
    g1_nonmember = bytes.fromhex(
        "8f304f6fcaea0518fd5e5ee3374cb756d7e11b1b7aa6540d48007596a28f5b37"
        "6b0404f2b09490b86b01a1c12a3a2107"
    )
    g2_nonmember = bytes.fromhex(
        "b148e74d5434b6b5f4ee9a0308b8d0a0711c718a9daaf919682204bbe0029715"
        "c54cb0e4bd1aa3f1fed0c435ff602bda0dfab9400ad67e72b1a4a4f93b91e572"
        "ebe718df3b74e9fbc056855fcb33444b25199d6011bb55f86d9deeee95da5109"
    )
    rc, _ = native.bls_g1_decompress(g1_nonmember, True)
    assert rc == -3, rc  # on curve, not in subgroup
    rc, _ = native.bls_g1_decompress(g1_nonmember, False)
    assert rc == 0  # decompression itself succeeds
    rc, _ = native.bls_g2_decompress(g2_nonmember, True)
    assert rc == -3, rc
    rc, _ = native.bls_g2_decompress(g2_nonmember, False)
    assert rc == 0


def test_native_verify_sets_matches_oracle():
    """The C pairing (round-3: dual Miller + cyclotomic final exp) must
    agree with the big-int oracle on valid, tampered, and edge inputs."""
    import numpy as np

    from lodestar_tpu import native
    from lodestar_tpu.bls import api as bls

    if not native.HAVE_NATIVE_BLS:
        import pytest

        pytest.skip("native extension unavailable")
    sk0, sk1 = bls.interop_secret_key(0), bls.interop_secret_key(1)
    msg = b"\x42" * 32
    pk = sk0.to_public_key().to_bytes()
    good = sk0.sign(msg).to_bytes()
    wrong = sk1.sign(msg).to_bytes()
    inf_sig = bytes([0xC0]) + b"\x00" * 95

    ok = native.bls_verify_sets(
        pk * 3, [msg, msg, b"\x43" * 32], good + wrong + good, bls.DST_G2
    )
    assert ok == [True, False, False]
    # infinity signature never verifies
    assert native.bls_verify_sets(pk, [msg], inf_sig, bls.DST_G2) == [False]
    # precomputed-H path agrees
    rc, h = native.bls_hash_to_g2(msg, bls.DST_G2)
    assert rc == 0
    ok2 = native.bls_verify_sets(
        pk * 2, [msg, msg], good + wrong, bls.DST_G2,
        np.stack([h, h])[:, 0], np.stack([h, h])[:, 1],
    )
    assert ok2 == [True, False]
    # api.verify now rides the native path — stays oracle-consistent
    assert bls.verify(sk0.to_public_key(), msg, sk0.sign(msg))
    assert not bls.verify(sk0.to_public_key(), msg, sk1.sign(msg))
