"""Eth1 deposit tracking: follower, block-production deposits that pass
process_operations proof checks, eth1 vote rule (reference: eth1 unit
tests + deposit inclusion e2e)."""


from lodestar_tpu.chain import BeaconChain
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.eth1 import Eth1DepositTracker, Eth1ProviderMock
from lodestar_tpu.params import DOMAIN_RANDAO
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state, process_slots
from lodestar_tpu.state_transition.block import _epoch_signing_root
from lodestar_tpu.state_transition.genesis import make_interop_deposits
from lodestar_tpu.types import get_types
from tests.test_chain import _sk

N = 16
SPE = MINIMAL.SLOTS_PER_EPOCH


def test_deposit_inclusion_via_block(tmp_path):
    """A new (17th) deposit flows: provider → tracker → produced block →
    process_operations (proof verified) → validator appears in the state."""
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    chain = BeaconChain(config, types, state)

    # provider has the 16 genesis deposits plus one new
    all_deposits = make_interop_deposits(config, types, N + 1)
    provider = Eth1ProviderMock()
    provider.add_block(b"\x42" * 32, 100, [d.data for d in all_deposits[:N]])
    provider.add_block(b"\x43" * 32, 200, [all_deposits[N].data])
    tracker = Eth1DepositTracker(config, types, provider)
    tracker.follow()
    assert len(tracker.deposit_datas) == N + 1

    # eth1 vote moves to the new block (no votes yet → provider's latest)
    vote = tracker.get_eth1_vote(chain.head_state.state, 0)
    assert vote.deposit_count == N + 1

    # produce a block that must include the pending deposit. The state's
    # accepted eth1_data is force-set (the voting-period majority path is
    # exercised separately below) BEFORE any slot processing so parent
    # roots line up.
    slot = 1
    base = chain.head_state.copy()
    base.state.eth1_data = vote.copy()
    pre = base.copy()
    process_slots(pre, types, slot)
    proposer = pre.epoch_ctx.get_beacon_proposer(slot)
    reveal = _sk(proposer).sign(
        _epoch_signing_root(0, config.get_domain(DOMAIN_RANDAO, slot))
    ).to_bytes()
    deposits = tracker.get_deposits_for_block(pre.state)
    assert len(deposits) == 1
    body = types.BeaconBlockBody(
        randao_reveal=reveal,
        eth1_data=vote.copy(),
        deposits=deposits,
    )
    block = types.BeaconBlock(
        slot=slot,
        proposer_index=proposer,
        parent_root=pre.state.latest_block_header.hash_tree_root(),
        body=body,
    )
    from lodestar_tpu.state_transition.stf import state_transition

    trial2 = base.copy()
    state_transition(
        trial2,
        types,
        types.SignedBeaconBlock(message=block.copy(), signature=b"\x00" * 96),
        verify_state_root=False,
        verify_signatures=False,
    )
    assert len(trial2.state.validators) == N + 1
    assert trial2.state.eth1_deposit_index == N + 1


def test_eth1_vote_majority():
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    provider = Eth1ProviderMock()
    provider.add_block(b"\x42" * 32, 100, [])
    tracker = Eth1DepositTracker(
        ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL), types, provider
    )
    candidate = types.Eth1Data(
        deposit_root=b"\x11" * 32, deposit_count=N, block_hash=b"\x22" * 32
    )
    state.eth1_data_votes = [candidate.copy(), candidate.copy(), types.Eth1Data()]
    vote = tracker.get_eth1_vote(state, 0)
    assert vote == candidate  # strict majority wins


def test_merge_block_tracker_finds_terminal_block():
    """Reference eth1MergeBlockTracker: first block crossing TTD with a
    sub-TTD parent is terminal; cached once found."""
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.eth1.merge_tracker import Eth1MergeBlockTracker, PowProviderMock

    import dataclasses

    config = dataclasses.replace(MINIMAL_CHAIN_CONFIG, TERMINAL_TOTAL_DIFFICULTY=100)
    provider = PowProviderMock()
    provider.add_block(b"\x01" * 32, b"\x00" * 32, 50)
    provider.add_block(b"\x02" * 32, b"\x01" * 32, 90)
    tracker = Eth1MergeBlockTracker(config, provider)
    assert tracker.get_terminal_pow_block() is None  # pre-merge

    provider.add_block(b"\x03" * 32, b"\x02" * 32, 120)  # crosses TTD
    provider.add_block(b"\x04" * 32, b"\x03" * 32, 150)  # descendant
    terminal = tracker.get_terminal_pow_block()
    assert terminal is not None and terminal.block_hash == b"\x03" * 32
    assert tracker.is_valid_terminal_pow_block(terminal)
    assert not tracker.is_valid_terminal_pow_block(provider.get_pow_block(b"\x04" * 32))
    # cached: provider changes don't disturb the found terminal block
    provider.add_block(b"\x05" * 32, b"\x04" * 32, 200)
    assert tracker.get_terminal_pow_block().block_hash == b"\x03" * 32


def test_exchange_transition_configuration_mock():
    """CL/EL merge-config handshake shape (engine_exchangeTransitionConfigurationV1)."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from lodestar_tpu.execution.engine import ExecutionEngineHttp

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length))
            assert req["method"] == "engine_exchangeTransitionConfigurationV1"
            echo = req["params"][0]
            raw = json.dumps({"jsonrpc": "2.0", "id": req["id"], "result": echo}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

    server = HTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        engine = ExecutionEngineHttp("127.0.0.1", server.server_address[1], b"\x00" * 32)
        assert engine.exchange_transition_configuration(1000, b"\x00" * 32)
    finally:
        server.shutdown()
        server.server_close()


def _abi_encode_bytes_fields(fields: list[bytes]) -> bytes:
    """ABI-encode n dynamic `bytes` values (DepositEvent data layout)."""
    n = len(fields)
    head = b""
    tail = b""
    off = 32 * n
    for f in fields:
        head += off.to_bytes(32, "big")
        padded = len(f).to_bytes(32, "big") + f + b"\x00" * ((32 - len(f) % 32) % 32)
        tail += padded
        off += len(padded)
    return head + tail


def test_http_provider_follows_real_json_rpc(tmp_path):
    """VERDICT round-1 missing #5: live JSON-RPC deposit follower — a mock
    HTTP server speaks eth_blockNumber/eth_getLogs/eth_getBlockByNumber/
    eth_call, Eth1ProviderHttp follows it, and the tracker ingests the
    deposits with correct little-endian amount/index decoding."""
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    from lodestar_tpu.eth1.provider import DEPOSIT_EVENT_TOPIC, Eth1ProviderHttp

    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    all_deposits = make_interop_deposits(config, types, N)

    # serve the deposits as eth_getLogs entries at block 5
    logs = []
    for i, d in enumerate(all_deposits):
        dd = d.data
        data = _abi_encode_bytes_fields(
            [
                bytes(dd.pubkey),
                bytes(dd.withdrawal_credentials),
                int(dd.amount).to_bytes(8, "little"),
                bytes(dd.signature),
                i.to_bytes(8, "little"),
            ]
        )
        logs.append(
            {
                "blockNumber": hex(5),
                "data": "0x" + data.hex(),
                "topics": [DEPOSIT_EVENT_TOPIC],
            }
        )
    calls = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            req = _json.loads(self.rfile.read(int(self.headers["Content-Length"])))
            method, params = req["method"], req["params"]
            calls.append(method)
            if method == "eth_blockNumber":
                result = hex(5 + 8)  # head; follow distance 8 → stable = 5
            elif method == "eth_getLogs":
                frm, to = int(params[0]["fromBlock"], 16), int(params[0]["toBlock"], 16)
                assert params[0]["address"] == "0x" + config.DEPOSIT_CONTRACT_ADDRESS.hex()
                result = [l for l in logs if frm <= int(l["blockNumber"], 16) <= to]
            elif method == "eth_getBlockByNumber":
                result = {
                    "number": params[0],
                    "hash": "0x" + (b"\x42" * 32).hex(),
                    "timestamp": hex(1_600_000_000),
                }
            elif method == "eth_call":
                sel = params[0]["data"]
                if sel == "0xc5f2892f":  # get_deposit_root
                    result = "0x" + (b"\x11" * 32).hex()
                else:  # get_deposit_count: ABI dynamic bytes8 LE
                    result = "0x" + _abi_encode_bytes_fields(
                        [len(logs).to_bytes(8, "little")]
                    ).hex()
            else:
                raise AssertionError(method)
            body = _json.dumps({"jsonrpc": "2.0", "id": req["id"], "result": result}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        provider = Eth1ProviderHttp(
            config, types, "127.0.0.1", srv.server_address[1],
            follow_distance=8, logs_batch_size=3,  # force chunked ranges
        )
        assert provider.latest_block_number() == 5
        tracker = Eth1DepositTracker(config, types, provider)
        tracker.follow()
        assert len(tracker.deposit_datas) == N
        assert bytes(tracker.deposit_datas[0].pubkey) == bytes(
            all_deposits[0].data.pubkey
        )
        assert tracker.deposit_datas[3].amount == all_deposits[3].data.amount
        blk = provider.get_block_by_number(5)
        assert blk.deposit_count == N and blk.deposit_root == b"\x11" * 32
        assert calls.count("eth_getLogs") >= 2  # chunking really happened
    finally:
        srv.shutdown()
