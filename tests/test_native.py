"""Native tier tests: differential vs hashlib / known vectors / roundtrips.

The native module replaces the reference's as-sha256, xxhash-wasm and
snappyjs deps (SURVEY.md §2.3); these tests pin its behavior to the
portable fallbacks and to published test vectors.
"""

import hashlib
import os

import pytest

from lodestar_tpu import native


def test_native_module_built():
    # the toolchain is baked into the image; the extension must compile
    assert native.HAVE_NATIVE, "native extension failed to build"


def test_sha256_matches_hashlib():
    for data in (b"", b"abc", b"x" * 63, b"y" * 64, b"z" * 1000, os.urandom(257)):
        assert native.sha256(data) == hashlib.sha256(data).digest()


def test_sha256_level_matches_pairwise():
    data = os.urandom(64 * 9)
    out = native.sha256_level(data)
    assert len(out) == 32 * 9
    for i in range(9):
        assert (
            out[32 * i : 32 * i + 32]
            == hashlib.sha256(data[64 * i : 64 * i + 64]).digest()
        )


def test_xxh64_known_vectors():
    # standard XXH64 reference vectors
    assert native.xxh64(b"", 0) == 0xEF46DB3751D8E999
    assert native.xxh64(b"a", 0) == 0xD24EC4F1A98C6E5B
    assert native.xxh64(b"abc", 0) == 0x44BC2CF5AD770999
    assert native.xxh64(b"", 1) == 0xD5AFBA1336A3BE4B


def test_xxh64_native_matches_python():
    for n in (0, 1, 3, 4, 7, 8, 15, 31, 32, 33, 63, 100, 1024):
        data = os.urandom(n)
        assert native.xxh64(data, 7) == native._xxh64_py(data, 7)


def test_snappy_roundtrip():
    cases = [
        b"",
        b"a",
        b"hello hello hello hello hello hello",
        b"\x00" * 100_000,
        os.urandom(1000),
        b"ab" * 40_000,
    ]
    for data in cases:
        comp = native.snappy_compress(data)
        assert native.snappy_uncompress(comp) == data
        # compressible inputs must actually compress
    rep = b"0123456789abcdef" * 4096
    assert len(native.snappy_compress(rep)) < len(rep) // 4


def test_snappy_cross_tier_roundtrip():
    # native-compressed streams must decode with the pure-Python decoder
    # and vice versa (same wire format)
    data = b"the quick brown fox " * 500
    assert native._snappy_uncompress_py(native.snappy_compress(data)) == data
    assert native.snappy_uncompress(native._snappy_compress_py(data)) == data


def test_snappy_rejects_corrupt():
    comp = bytearray(native.snappy_compress(b"hello world, hello world"))
    comp[-1] ^= 0xFF
    with pytest.raises(ValueError):
        native.snappy_uncompress(bytes(comp) + b"\x90\x90\x90\x90")


def test_ssz_backend_install():
    from lodestar_tpu.ssz import hashing

    before = hashing.merkleize_chunks([b"\x01" * 32, b"\x02" * 32])
    native.install_ssz_backend()
    try:
        after = hashing.merkleize_chunks([b"\x01" * 32, b"\x02" * 32])
        assert before == after
    finally:
        hashing.set_hash_backend(hashing.hash_level)
