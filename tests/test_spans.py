"""Lifecycle tracing (ISSUE 2): span nesting across asyncio tasks and
executor threads, ring-buffer retention, disabled-mode zero overhead,
slot-milestone emission on a stubbed block import, /debug/traces
retrieval, trace-id log correlation, and the _verify_now **kwargs
facade regression (ADVICE round 5).

Kernels are stubbed (MockBlsVerifier) — the span layer is pure host
bookkeeping and must be testable without a device.
"""

import asyncio
import json
import logging
import threading
import time
import urllib.request

import pytest

from lodestar_tpu.observability import spans
from lodestar_tpu.observability.spans import Tracer


# --- core span mechanics -----------------------------------------------------


def test_span_nesting_and_parentage():
    t = Tracer(capacity=8)
    with t.trace("root", kind="test"):
        with t.span("child"):
            with t.span("grandchild"):
                pass
        with t.span("sibling"):
            pass
    docs = t.traces()
    assert len(docs) == 1
    by_name = {s["name"]: s for s in docs[0]["spans"]}
    assert set(by_name) == {"root", "child", "grandchild", "sibling"}
    root_id = by_name["root"]["span_id"]
    assert by_name["child"]["parent_id"] == root_id
    assert by_name["sibling"]["parent_id"] == root_id
    assert by_name["grandchild"]["parent_id"] == by_name["child"]["span_id"]
    assert by_name["root"]["parent_id"] is None
    # every span carries the root's trace id implicitly: one doc, one id
    assert docs[0]["trace_id"]


def test_span_nesting_across_asyncio_tasks():
    """Tasks created inside a span copy the context at creation time, so
    concurrent children correlate under the same trace root."""
    t = Tracer(capacity=8)

    async def main():
        with t.trace("root"):
            async def child(i):
                with t.span(f"task{i}"):
                    await asyncio.sleep(0.01)

            await asyncio.gather(child(0), child(1), child(2))

    asyncio.run(main())
    (doc,) = t.traces()
    names = {s["name"] for s in doc["spans"]}
    assert names == {"root", "task0", "task1", "task2"}
    root_id = next(s["span_id"] for s in doc["spans"] if s["name"] == "root")
    for s in doc["spans"]:
        if s["name"] != "root":
            assert s["parent_id"] == root_id


def test_cross_thread_context_attach():
    """Executor threads don't inherit contextvars; context()/attach()
    is the explicit handoff the gossip handler uses."""
    t = Tracer(capacity=8)
    seen = {}
    with t.trace("root"):
        ctx = t.context()

        def work():
            # without attach: no active span in this thread
            seen["before"] = t.current_trace_id()
            with t.attach(ctx), t.span("worker"):
                seen["inside"] = t.current_trace_id()

        th = threading.Thread(target=work)
        th.start()
        th.join()
    (doc,) = t.traces()
    assert seen["before"] is None
    assert seen["inside"] == doc["trace_id"]
    assert {s["name"] for s in doc["spans"]} == {"root", "worker"}


def test_ring_buffer_eviction_keeps_newest():
    t = Tracer(capacity=4)
    for i in range(10):
        with t.trace(f"t{i}"):
            pass
    docs = t.traces(limit=100)
    assert len(docs) == 4
    assert [d["name"] for d in docs] == ["t9", "t8", "t7", "t6"]
    assert t.completed_total == 10


def test_disabled_mode_zero_overhead():
    t = Tracer(enabled=False)
    # one shared null singleton: no allocation per call
    assert t.span("a") is t.span("b") is t.trace("c")
    with t.trace("x"):
        with t.span("y"):
            pass
    assert t.traces() == []
    assert t.context() is None
    assert t.current_trace_id() is None
    with t.attach(None):
        pass  # no-op, no error
    # annotate/event on the null span are no-ops too
    t.span("z").annotate(slot=1).event("e")


def test_error_status_and_filtering():
    t = Tracer(capacity=8)
    with pytest.raises(RuntimeError):
        with t.trace("bad", slot=3):
            raise RuntimeError("boom")
    with t.trace("good"):
        t.annotate(slot=4, root="ab" * 16)
    assert t.traces(slot=3)[0]["spans"][0]["status"] == "error"
    assert "boom" in t.traces(slot=3)[0]["spans"][0]["attrs"]["error"]
    assert t.traces(slot=4)[0]["name"] == "good"
    assert t.traces(root="0x" + "ab" * 16)[0]["name"] == "good"
    assert t.traces(slot=99) == []


def test_child_attrs_promote_to_trace_root():
    """slot/root learned mid-trace (after decode) must make the whole
    trace filterable."""
    t = Tracer(capacity=8)
    with t.trace("gossip/beacon_block", kind="beacon_block"):
        with t.span("validation/block", slot=11):
            pass
    (doc,) = t.traces(slot=11)
    assert doc["slot"] == 11 and doc["attrs"]["kind"] == "beacon_block"


def test_on_finish_callbacks_fire():
    t = Tracer(capacity=8)
    kinds = []
    t.on_finish.append(lambda doc: kinds.append(doc["name"]))
    with t.trace("a"):
        pass
    assert kinds == ["a"]


# --- logger correlation ------------------------------------------------------


def test_logger_injects_trace_id():
    from lodestar_tpu.utils.logger import _TraceContextFilter

    f = _TraceContextFilter()
    rec = logging.LogRecord("n", logging.INFO, "p", 1, "msg", (), None)
    f.filter(rec)
    assert rec.trace == ""  # outside any trace
    with spans.tracer.trace("log-test"):
        tid = spans.current_trace_id()
        rec2 = logging.LogRecord("n", logging.INFO, "p", 1, "msg", (), None)
        f.filter(rec2)
        assert rec2.trace == f" [t:{tid[:8]}]"


# --- the acceptance path: stubbed block import -> one correlated trace -------


@pytest.fixture(scope="module")
def traced_chain():
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.chain.bls_verifier import MockBlsVerifier
    from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.metrics import create_beacon_metrics
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.state_transition import interop_genesis_state
    from lodestar_tpu.types import get_types

    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(
        fork_config, types, 16, genesis_time=1_600_000_000
    )
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    chain = BeaconChain(config, types, state, verifier=MockBlsVerifier())
    chain.metrics = create_beacon_metrics()
    chain.clock.set_slot(1)
    return config, types, chain


def test_stubbed_block_import_produces_correlated_trace(traced_chain):
    """ISSUE 2 acceptance: one gossip-driven block import = one trace
    with >= 5 spans (decode, validation, bls-verify, fork-choice,
    import) retrievable from /debug/traces, plus the five slot-milestone
    delay series on /metrics."""
    from lodestar_tpu.metrics import MetricsRegistry, MetricsServer
    from lodestar_tpu.network.gossip.encoding import encode_message
    from lodestar_tpu.network.gossip.gossipsub import ValidationResult
    from lodestar_tpu.network.gossip.handlers import GossipHandlers
    from lodestar_tpu.network.gossip.topic import GossipTopic, GossipType

    config, types, chain = traced_chain
    block = chain.produce_block(1, randao_reveal=b"\x00" * 96)
    signed = types.SignedBeaconBlock(message=block, signature=b"\x11" * 96)
    wire = encode_message(signed.serialize())
    topic = GossipTopic(GossipType.beacon_block, b"\x01\x02\x03\x04")

    spans.tracer.clear()
    handlers = GossipHandlers(config, types, chain)
    result = asyncio.run(handlers._process((topic, wire)))
    assert result is ValidationResult.ACCEPT

    docs = spans.tracer.traces(slot=1)
    assert docs, "gossip import produced no trace"
    doc = docs[0]
    names = [s["name"] for s in doc["spans"]]
    for required in (
        "gossip/decode",
        "validation/block",
        "chain/bls_verify",
        "chain/fork_choice",
        "chain/import",
    ):
        assert required in names, f"{required} missing from {names}"
    assert len(doc["spans"]) >= 5
    assert doc["root"] == block.hash_tree_root().hex()
    # filterable by root as served over HTTP
    srv = MetricsServer(MetricsRegistry(), port=0, tracer=spans.tracer)
    srv.start()
    try:
        url = (
            f"http://127.0.0.1:{srv.port}/debug/traces"
            f"?root=0x{doc['root']}&limit=5"
        )
        with urllib.request.urlopen(url) as r:
            served = json.load(r)
        assert served["count"] >= 1
        assert served["traces"][0]["trace_id"] == doc["trace_id"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/traces?slot=999999"
        ) as r:
            assert json.load(r)["count"] == 0
    finally:
        srv.close()

    # slot-milestone delays render on /metrics, one series per milestone
    text = chain.metrics.registry.expose()
    for milestone in spans.MILESTONES:
        assert (
            f'lodestar_slot_milestone_last_delay_seconds{{milestone="{milestone}"}}'
            in text
        ), milestone
    assert 'lodestar_slot_milestone_delay_seconds_bucket' in text
    # milestones are also trace events, timestamped within the trace
    events = [e["name"] for s in doc["spans"] for e in s.get("events", [])]
    for milestone in spans.MILESTONES:
        assert milestone in events


def test_milestones_skipped_for_historic_blocks(traced_chain):
    """Range-sync imports of old blocks must not pollute the milestone
    histograms with hours-old 'delays'."""
    config, types, chain = traced_chain
    before = chain.metrics.slot_milestone_seconds._totals.copy()
    chain._record_milestone("imported", chain.clock.current_slot - 5)
    assert chain.metrics.slot_milestone_seconds._totals == before
    chain._record_milestone("imported", chain.clock.current_slot)
    key = ("imported",)
    assert chain.metrics.slot_milestone_seconds._totals[key] == \
        before.get(key, 0) + 1


# --- _verify_now facade detection (ADVICE round 5) ---------------------------


def test_verify_now_uses_batchable_false_through_kwargs_facade():
    """A wrapper that only exposes **kwargs must still receive
    batchable=False on the latency-critical import path."""
    from lodestar_tpu.chain.chain import _verify_now

    calls = []

    class KwargsFacade:
        def verify_signature_sets(self, sets, **kwargs):
            calls.append(kwargs)
            return True

    assert _verify_now(KwargsFacade(), [object()]) is True
    assert calls == [{"batchable": False}]

    class ExplicitFacade:
        def verify_signature_sets(self, sets, batchable=True):
            calls.append({"batchable": batchable})
            return True

    assert _verify_now(ExplicitFacade(), [object()]) is True
    assert calls[-1] == {"batchable": False}

    class BareFacade:
        def verify_signature_sets(self, sets):
            calls.append("bare")
            return True

    assert _verify_now(BareFacade(), [object()]) is True
    assert calls[-1] == "bare"
