"""Gossip layer tests: topics, msg-id functions, and the attestation /
block validation ladders (reference: network/gossip unit tests +
chain/validation unit tests)."""

import hashlib

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain import BeaconChain
from lodestar_tpu.chain.validation import (
    GossipAction,
    compute_subnet_for_attestation,
    validate_gossip_attestation,
    validate_gossip_block,
)
from lodestar_tpu.config.beacon_config import (
    BeaconConfig,
    ChainForkConfig,
    compute_signing_root,
)
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.network.gossip import (
    GossipTopic,
    GossipType,
    compute_msg_id,
    decode_message,
    encode_message,
    fast_msg_id,
    parse_topic,
    stringify_topic,
)
from lodestar_tpu.params import DOMAIN_BEACON_ATTESTER
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.types import get_types

SPE = MINIMAL.SLOTS_PER_EPOCH


def test_topic_roundtrip():
    digest = b"\x01\x02\x03\x04"
    t1 = GossipTopic(GossipType.beacon_block, digest)
    s1 = stringify_topic(t1)
    assert s1 == "/eth2/01020304/beacon_block/ssz_snappy"
    assert parse_topic(s1) == t1

    t2 = GossipTopic(GossipType.beacon_attestation, digest, subnet=13)
    s2 = stringify_topic(t2)
    assert "_13/" in s2
    assert parse_topic(s2) == t2

    with pytest.raises(ValueError):
        stringify_topic(GossipTopic(GossipType.beacon_attestation, digest))
    with pytest.raises(ValueError):
        parse_topic("/eth1/01020304/beacon_block/ssz_snappy")


def test_message_encoding_roundtrip_and_msg_ids():
    payload = b"ssz bytes " * 100
    wire = encode_message(payload)
    assert decode_message(wire) == payload
    assert isinstance(fast_msg_id(wire), int)

    topic = "/eth2/01020304/beacon_block/ssz_snappy"
    mid = compute_msg_id(topic, wire)
    assert len(mid) == 20
    # spec formula reproduced independently
    expected = hashlib.sha256(
        b"\x01\x00\x00\x00"
        + len(topic.encode()).to_bytes(8, "little")
        + topic.encode()
        + payload
    ).digest()[:20]
    assert mid == expected
    # invalid snappy falls back to the INVALID domain over raw data
    bad_wire = b"\xff\xff\xff\xff\xff"
    mid_bad = compute_msg_id(topic, bad_wire)
    expected_bad = hashlib.sha256(
        b"\x00\x00\x00\x00"
        + len(topic.encode()).to_bytes(8, "little")
        + topic.encode()
        + bad_wire
    ).digest()[:20]
    assert mid_bad == expected_bad


@pytest.fixture(scope="module")
def chain_setup():
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, 16, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    chain = BeaconChain(config, types, state)
    chain.clock.set_slot(1)
    return config, types, chain


def _make_single_attestation(config, types, chain, slot=0, flip_sig=False):
    """A single-bit gossip attestation by the first member of committee 0."""
    cached = chain.head_state
    ctx = cached.epoch_ctx
    epoch = slot // SPE
    committee = ctx.get_beacon_committee(slot, 0)
    head_root = chain.head_root
    data = types.AttestationData(
        slot=slot,
        index=0,
        beacon_block_root=head_root,
        source=cached.state.current_justified_checkpoint.copy(),
        target=types.Checkpoint(epoch=epoch, root=head_root),
    )
    domain = config.get_domain(DOMAIN_BEACON_ATTESTER, slot, epoch)
    root = compute_signing_root(data.hash_tree_root(), domain)
    signer = int(committee[0])
    sk = bls.interop_secret_key(signer + (99 if flip_sig else 0))
    bits = [False] * len(committee)
    bits[0] = True
    return types.Attestation(
        aggregation_bits=bits, data=data, signature=sk.sign(root).to_bytes()
    ), signer


def test_validate_attestation_accept_then_duplicate(chain_setup):
    config, types, chain = chain_setup
    att, signer = _make_single_attestation(config, types, chain)
    subnet = compute_subnet_for_attestation(
        chain.head_state.epoch_ctx, att.data.slot, 0, MINIMAL
    )
    res = validate_gossip_attestation(chain, types, att, subnet)
    assert res.action == GossipAction.ACCEPT, res.reason
    assert res.attesting_index == signer
    # same attester again → IGNORE (seen cache)
    res2 = validate_gossip_attestation(chain, types, att, subnet)
    assert res2.action == GossipAction.IGNORE


def test_validate_attestation_reject_paths(chain_setup):
    config, types, chain = chain_setup
    att, _ = _make_single_attestation(config, types, chain)
    subnet = compute_subnet_for_attestation(
        chain.head_state.epoch_ctx, att.data.slot, 0, MINIMAL
    )

    # two bits set → REJECT
    att2 = att.copy()
    bits = list(att2.aggregation_bits)
    bits[1] = True
    att2.aggregation_bits = bits
    assert (
        validate_gossip_attestation(chain, types, att2, subnet).action
        == GossipAction.REJECT
    )

    # wrong subnet → REJECT
    att3, _ = _make_single_attestation(config, types, chain)
    assert (
        validate_gossip_attestation(chain, types, att3, subnet + 1).action
        == GossipAction.REJECT
    )

    # unknown head block → IGNORE
    att4, _ = _make_single_attestation(config, types, chain)
    att4.data.beacon_block_root = b"\x77" * 32
    assert (
        validate_gossip_attestation(chain, types, att4, subnet).action
        == GossipAction.IGNORE
    )

    # bad signature → REJECT (use a different committee member so the seen
    # cache doesn't IGNORE first)
    att5, _ = _make_single_attestation(config, types, chain, flip_sig=True)
    bits = [False] * len(att5.aggregation_bits)
    bits[1] = True
    att5.aggregation_bits = bits
    assert (
        validate_gossip_attestation(chain, types, att5, subnet).action
        == GossipAction.REJECT
    )


def test_validate_block_ladder(chain_setup):
    config, types, chain = chain_setup
    # unknown parent → IGNORE
    blk = types.SignedBeaconBlock()
    blk.message.slot = 1
    blk.message.parent_root = b"\x55" * 32
    assert validate_gossip_block(chain, types, blk).action == GossipAction.IGNORE
    # future slot → IGNORE
    blk2 = types.SignedBeaconBlock()
    blk2.message.slot = 99
    assert validate_gossip_block(chain, types, blk2).action == GossipAction.IGNORE
