"""Memory profiling harness (reference §4.7: `beacon-node/test/memory/`).

tracemalloc-based growth checks on the hot in-memory structures: repeated
state copies and cache churn must not leak — the role of the reference's
heap-profiling scripts.
"""

import gc
import tracemalloc

import pytest

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow



def _measure_growth(fn, cycles=6, warmup=2):
    """Peak RSS-ish growth (tracemalloc current bytes) across cycles after
    warmup; returns bytes grown between cycle `warmup` and the last."""
    for _ in range(warmup):
        fn()
    gc.collect()
    tracemalloc.start()
    baseline = None
    for i in range(cycles):
        fn()
        gc.collect()
        current, _peak = tracemalloc.get_traced_memory()
        if baseline is None:
            baseline = current
    growth = current - baseline
    tracemalloc.stop()
    return growth


def test_state_copy_does_not_leak():
    from tests.test_network_live import _fresh_chain

    config, types, chain = _fresh_chain()

    def cycle():
        st = chain.head_state.copy()
        st.sync_flat()

    growth = _measure_growth(cycle)
    assert growth < 2_000_000, f"state copies leak: {growth} bytes over cycles"


def test_state_cache_bounded():
    """StateContextCache must evict at its max size (reference LRU 96)."""
    from lodestar_tpu.chain.state_cache import StateContextCache
    from tests.test_network_live import _fresh_chain

    config, types, chain = _fresh_chain()
    cache = StateContextCache()
    st = chain.head_state
    cap = cache.max_states
    for i in range(cap + 20):
        cache.add(i.to_bytes(32, "big"), st, block_root=i.to_bytes(32, "big"))
    assert len(cache._cache) <= cap


def test_seen_caches_prune_bounded():
    from lodestar_tpu.chain.seen_cache import SeenAttesters

    seen = SeenAttesters()
    for epoch in range(50):
        for idx in range(64):
            seen.add(epoch, idx)
    seen.prune(finalized_epoch=48)
    assert set(seen._by_epoch) == {48, 49}
