"""Differential tests for the VMEM-resident MXU Montgomery multiply
(`ops/pallas_mxu.py`) against the word-serial scan oracle (`fp._mul_scan`).

On the CPU backend the kernel runs through the Pallas interpreter
(identical jnp semantics); on real TPU (LODESTAR_TPU_TEST_PLATFORM=axon)
the compiled Mosaic kernel is exercised — that path is where the
left-shift-on-sliced-operand miscompile guard matters (see the
MOSAIC MISCOMPILE GUARD note in `_mxu_kernel`: `x << 16` on a sliced
matmul output silently lowered to 0 at tile heights >= 64, v5e 2026-07;
recombinations must stay integer multiplies).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lodestar_tpu.bls.fields import P
from lodestar_tpu.ops import fp
from lodestar_tpu.ops.limbs import N_LIMBS, int_to_limbs, limbs_to_int
from lodestar_tpu.ops.pallas_mxu import mont_mul


def _rand_elems(rng, n, hi):
    vals = [int(rng.integers(0, 2**62)) ** 7 % hi for _ in range(n)]
    return vals, jnp.asarray(np.stack([int_to_limbs(v) for v in vals]))


@pytest.mark.parametrize("n", [1, 8, 37, 256, 300])
def test_mont_mul_matches_scan(n):
    rng = np.random.default_rng(n)
    _, a = _rand_elems(rng, n, 2 * P)
    _, b = _rand_elems(rng, n, 2 * P)
    ref = np.asarray(fp._mul_scan(a, b))
    got = np.asarray(mont_mul(a, b))
    assert (ref == got).all()


def test_mont_mul_edge_values():
    # 0, 1, p-1, p, 2p-1 in all pairings: the [0, 2p) contract's corners
    vals = [0, 1, P - 1, P, 2 * P - 1]
    a = jnp.asarray(np.stack([int_to_limbs(x) for x in vals for _ in vals]))
    b = jnp.asarray(np.stack([int_to_limbs(y) for _ in vals for y in vals]))
    ref = np.asarray(fp._mul_scan(a, b))
    got = np.asarray(mont_mul(a, b))
    assert (ref == got).all()
    # outputs respect the lazy-reduction bound and the ring semantics
    R_inv = pow(1 << 384, -1, P)
    for i, (x, y) in enumerate([(x, y) for x in vals for y in vals]):
        out = limbs_to_int(np.asarray(got[i]))
        assert out < 2 * P
        assert out % P == (x * y * R_inv) % P


def test_mont_mul_broadcasting_and_stacks():
    """The tower stacks muls on leading axes (fp2.mul: (3, batch, 32));
    the wrapper must flatten/broadcast identically to fp.mul."""
    rng = np.random.default_rng(7)
    _, a = _rand_elems(rng, 6, 2 * P)
    _, b = _rand_elems(rng, 6, 2 * P)
    a3 = a.reshape(3, 2, N_LIMBS)
    b3 = b.reshape(3, 2, N_LIMBS)
    ref = np.asarray(fp._mul_scan(a3, b3))
    got = np.asarray(mont_mul(a3, b3))
    assert ref.shape == got.shape == (3, 2, N_LIMBS)
    assert (ref == got).all()
    # broadcast one operand over the stack axis
    ref_b = np.asarray(fp._mul_scan(a3, b3[0]))
    got_b = np.asarray(mont_mul(a3, b3[0]))
    assert (ref_b == got_b).all()


def test_mont_mul_chain_against_oracle():
    """A short dependency chain (the Miller loop's shape of reuse):
    errors that cancel on one multiply would compound here."""
    rng = np.random.default_rng(11)
    vals, a = _rand_elems(rng, 16, 2 * P)
    bvals, b = _rand_elems(rng, 16, 2 * P)
    x = a
    for _ in range(5):
        x = mont_mul(x, b)
    R_inv = pow(1 << 384, -1, P)
    got = np.asarray(x)
    for i in range(16):
        exp = vals[i]
        for _ in range(5):
            exp = exp * bvals[i] * R_inv % P
        assert limbs_to_int(got[i]) % P == exp
