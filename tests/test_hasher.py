"""Differential tests: incremental columnar state hashing vs the plain SSZ
recompute (the oracle), plus the O(dirty·log n) property.

Reference analog: `@chainsafe/ssz` ViewDU commit+hashTreeRoot — the
incremental path must be bit-identical to a full merkleization
(stateTransition.ts:69-74; SURVEY hard-part #7).
"""

import numpy as np
import pytest

from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.ssz.hashing import merkleize_chunks
from lodestar_tpu.ssz.tree_cache import ChunkTree
from lodestar_tpu.state_transition import CachedBeaconState, interop_genesis_state
from lodestar_tpu.types import get_types


# --- ChunkTree vs merkleize_chunks ------------------------------------------


def _rand_chunks(rng, n):
    return rng.integers(0, 256, size=(n, 32), dtype=np.int64).astype(np.uint8)


@pytest.mark.parametrize("n,limit", [(0, 8), (1, 8), (5, 8), (8, 8), (7, 1024)])
def test_chunk_tree_matches_merkleize(n, limit):
    rng = np.random.default_rng(n * 31 + limit)
    leaves = _rand_chunks(rng, n)
    t = ChunkTree(limit)
    t.update(leaves)
    assert t.root() == merkleize_chunks(leaves.tobytes(), limit=limit)


def test_chunk_tree_incremental_updates():
    rng = np.random.default_rng(3)
    t = ChunkTree(64)
    leaves = _rand_chunks(rng, 10)
    t.update(leaves)
    # mutate one chunk
    leaves = leaves.copy()
    leaves[7] = _rand_chunks(rng, 1)[0]
    t.update(leaves)
    assert t.root() == merkleize_chunks(leaves.tobytes(), limit=64)
    # append
    leaves = np.concatenate([leaves, _rand_chunks(rng, 5)])
    t.update(leaves)
    assert t.root() == merkleize_chunks(leaves.tobytes(), limit=64)
    # shrink (rebuild path)
    leaves = leaves[:4]
    t.update(leaves)
    assert t.root() == merkleize_chunks(leaves.tobytes(), limit=64)
    # no-op update keeps the cached root
    t.update(leaves)
    assert t.root() == merkleize_chunks(leaves.tobytes(), limit=64)


def test_chunk_tree_rehash_is_dirty_bounded():
    """One changed leaf re-hashes one path, not the whole tree."""
    import lodestar_tpu.ssz.tree_cache as tc

    rng = np.random.default_rng(9)
    t = ChunkTree(1 << 14)
    leaves = _rand_chunks(rng, 1 << 12)  # 4096 chunks
    t.update(leaves)
    calls = []
    orig = tc._hash_rows

    def counting(pairs):
        calls.append(len(pairs) // 64 if pairs.ndim == 1 else len(pairs))
        return orig(pairs)

    tc._hash_rows = counting
    try:
        leaves = leaves.copy()
        leaves[1234] ^= 0xFF
        t.update(leaves)
        t.root()
    finally:
        tc._hash_rows = orig
    # 12 tree levels × 1 dirty parent each (+ virtual-padding folds use
    # hash_pair, not _hash_rows)
    assert sum(calls) <= 14


# --- state hashing through the STF caches -----------------------------------


@pytest.fixture(scope="module", params=["phase0", "altair"])
def cached_state(request):
    types = getattr(get_types(MINIMAL), request.param)
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(
        fork_config, types, 16, genesis_time=1_600_000_000
    )
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    return CachedBeaconState(config, state, MINIMAL)


def test_state_root_matches_plain(cached_state):
    cached = cached_state
    assert cached.hash_tree_root() == cached.state.hash_tree_root()


def test_state_root_tracks_mutations(cached_state):
    cached = cached_state
    # balance change through the flat column
    cached.flat.balances[3] += 12345
    assert cached.hash_tree_root() == cached.state.hash_tree_root()
    # validator column change (exit)
    cached.flat.exit_epoch[2] = 77
    cached.flat.withdrawable_epoch[2] = 99
    assert cached.hash_tree_root() == cached.state.hash_tree_root()
    # slot + block_roots rotation (vector field)
    st = cached.state
    st.slot = st.slot + 1
    st.block_roots[1] = b"\x42" * 32
    st.state_roots[1] = b"\x43" * 32
    assert cached.hash_tree_root() == cached.state.hash_tree_root()
    # withdrawal credential rewrite through the flat column
    cached.flat.withdrawal_credentials[5] = np.frombuffer(b"\x01" * 32, np.uint8)
    assert cached.hash_tree_root() == cached.state.hash_tree_root()
    # participation flags (altair columns), if present
    if cached.is_altair:
        cached.current_participation[4] = 7
        cached.inactivity_scores[1] = 5
        assert cached.hash_tree_root() == cached.state.hash_tree_root()


def test_state_root_tracks_append(cached_state):
    cached = cached_state
    st = cached.state
    v = st.validators[0].copy()
    v.pubkey = bytes([7]) * 48
    st.validators.append(v)
    st.balances.append(32_000_000_000)
    cached.flat.append(v, 32_000_000_000)
    if cached.is_altair:
        st.previous_epoch_participation.append(0)
        st.current_epoch_participation.append(0)
        st.inactivity_scores.append(0)
        cached.previous_participation = np.append(
            cached.previous_participation, np.uint8(0)
        )
        cached.current_participation = np.append(
            cached.current_participation, np.uint8(0)
        )
        cached.inactivity_scores = np.append(
            cached.inactivity_scores, np.uint64(0)
        )
    assert cached.hash_tree_root() == cached.state.hash_tree_root()
