"""Differential tests: device pairing (ops/pairing.py) vs CPU oracle.

The device Miller loop scales its line functions by w³ and by Fp/Fp2
denominators — factors annihilated by the final exponentiation — so raw
Miller outputs are NOT comparable to the oracle; only post-final-exp values
are. `final_exponentiation` itself is the same function in both tiers
(HHT hard part computing pairing³) and is compared bit-for-bit.

Everything runs under jit: the eager path dispatches tens of thousands of
tiny ops and is orders of magnitude slower even on CPU.
"""

import pytest

import jax
import numpy as np

from lodestar_tpu.bls import api as bls
from lodestar_tpu.bls import curve as oc
from lodestar_tpu.bls import pairing as op
from lodestar_tpu.bls.hash_to_curve import hash_to_g2
from lodestar_tpu.ops import fp
from lodestar_tpu.ops import pairing as dp
from lodestar_tpu.ops.io_host import (
    fq12_to_limbs,
    g1_affine_to_limbs,
    g2_affine_to_limbs,
    limbs_to_fq12,
)
from lodestar_tpu.ops.points import G1_GEN_X, G1_GEN_Y

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow


RNG = np.random.default_rng(99)

_pairing_jit = jax.jit(lambda p, q: dp.pairing(p, q))
_finalexp_jit = jax.jit(dp.final_exponentiation)
_check2_jit = jax.jit(dp.pairing_check)


def _rand_g1():
    return oc.PointG1.generator() * int(RNG.integers(2, 2**62))


def _rand_g2():
    return oc.PointG2.generator() * int(RNG.integers(2, 2**62))


def _aff(p, g2=False):
    x, y, _ = (g2_affine_to_limbs if g2 else g1_affine_to_limbs)(p)
    return np.asarray(x), np.asarray(y)


def test_final_exponentiation_matches_oracle():
    p, q = _rand_g1(), _rand_g2()
    f = op.miller_loop(p, q)
    got = limbs_to_fq12(np.asarray(_finalexp_jit(fq12_to_limbs(f))))
    assert got == op.final_exponentiation(f)


def test_pairing_matches_oracle():
    p, q = _rand_g1(), _rand_g2()
    got = limbs_to_fq12(np.asarray(_pairing_jit(_aff(p), _aff(q, g2=True))))
    assert got == op.pairing(p, q)


def test_pairing_bilinearity_on_device():
    # e(aP, Q) == e(P, aQ) — both sides computed wholly on device. Compare
    # with fp12.eq (canonicalizing): raw limb arrays are NOT unique under
    # lazy reduction (each element has representations x and x+p).
    from lodestar_tpu.ops import fp12

    p, q = _rand_g1(), _rand_g2()
    a = 7
    lhs = _pairing_jit(_aff(p * a), _aff(q, g2=True))
    rhs = _pairing_jit(_aff(p), _aff(q * a, g2=True))
    assert bool(jax.jit(fp12.eq)(lhs, rhs))


def _neg_g1_aff():
    return np.asarray(G1_GEN_X), np.asarray(jax.jit(fp.neg)(G1_GEN_Y))


def test_pairing_check_signature_equation():
    # e(pk, H(m)) · e(−g1, sig) == 1 for a real BLS signature, batched lanes.
    sk = bls.interop_secret_key(0)
    pk = sk.to_public_key()
    msg = b"\x42" * 32
    sig = sk.sign(msg)
    h = hash_to_g2(msg)

    neg_g1 = _neg_g1_aff()
    pk_aff = _aff(pk.point)
    h_aff = _aff(h, g2=True)
    sig_aff = _aff(sig.point, g2=True)

    xs = np.stack([pk_aff[0], neg_g1[0]])
    ys = np.stack([pk_aff[1], neg_g1[1]])
    qx = np.stack([h_aff[0], sig_aff[0]])
    qy = np.stack([h_aff[1], sig_aff[1]])
    mask = np.array([True, True])
    assert bool(_check2_jit((xs, ys), (qx, qy), mask))

    # wrong message must fail
    h_bad = _aff(hash_to_g2(b"\x43" * 32), g2=True)
    qx_bad = np.stack([h_bad[0], sig_aff[0]])
    qy_bad = np.stack([h_bad[1], sig_aff[1]])
    assert not bool(_check2_jit((xs, ys), (qx_bad, qy_bad), mask))


def test_pairing_check_masked_lane_is_identity():
    # A masked-out (padding) lane must not affect the product.
    garbage_p, garbage_q = _aff(_rand_g1()), _aff(_rand_g2(), g2=True)
    sk = bls.interop_secret_key(3)
    pk = sk.to_public_key()
    msg = b"\x07" * 32
    sig = sk.sign(msg)
    neg_g1 = _neg_g1_aff()
    h_aff = _aff(hash_to_g2(msg), g2=True)
    sig_aff = _aff(sig.point, g2=True)

    xs = np.stack([_aff(pk.point)[0], neg_g1[0], garbage_p[0]])
    ys = np.stack([_aff(pk.point)[1], neg_g1[1], garbage_p[1]])
    qx = np.stack([h_aff[0], sig_aff[0], garbage_q[0]])
    qy = np.stack([h_aff[1], sig_aff[1], garbage_q[1]])
    mask = np.array([True, True, False])
    assert bool(_check2_jit((xs, ys), (qx, qy), mask))


def test_final_exponentiation_batch_bit_identical():
    """The shared-easy-part batched final exp (fp12.batch_inv Montgomery
    product trick — the bisection probe kernel's entry) must equal the
    per-lane final_exponentiation bit-for-bit, including identity lanes
    (the probe padding)."""
    from lodestar_tpu.ops import fp12

    ms = []
    for _ in range(3):
        p, q = _rand_g1(), _rand_g2()
        ms.append(fq12_to_limbs(op.miller_loop(p, q)))
    ms.append(np.asarray(fp12.one(())))  # identity padding lane
    fs = np.stack(ms)
    per_lane = np.asarray(fp.canonical(_finalexp_jit(fs)))
    batched = np.asarray(
        fp.canonical(jax.jit(dp.final_exponentiation_batch)(fs))
    )
    assert np.array_equal(per_lane, batched)
    # identity lane passes is_one through the batch entry
    assert bool(
        np.asarray(fp12.is_one(jax.jit(dp.final_exponentiation_batch)(fs)))[-1]
    )
