"""tools/bench_compare.py: the CI benchmark regression gate (ISSUE 2;
reference analog `.benchrc.yaml` 3x threshold) exercised on synthetic
BENCH histories and on the repo's committed history."""

import importlib.util
import json
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    path = os.path.join(REPO_ROOT, "tools", "bench_compare.py")
    spec = importlib.util.spec_from_file_location("bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _round(tmp_path, n, value, phases=None, parsed=True, extra=None):
    doc = {"n": n, "rc": 0 if parsed else 124, "parsed": None}
    if parsed:
        doc["parsed"] = {
            "metric": "bls_signature_sets_verified_per_sec",
            "value": value,
            "unit": "sets/s",
        }
        if phases:
            doc["parsed"]["phases"] = phases
        if extra:
            doc["parsed"].update(extra)
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(doc))


def test_exits_nonzero_on_3x_regression(tmp_path, capsys):
    mod = _load()
    _round(tmp_path, 1, 9000.0)
    _round(tmp_path, 2, 2000.0)  # 4.5x drop
    assert mod.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "bls_signature_sets_verified_per_sec" in out


def test_exits_zero_on_improvement_and_mild_drop(tmp_path, capsys):
    mod = _load()
    _round(tmp_path, 1, 8000.0)
    _round(tmp_path, 2, 9000.0)  # improvement
    assert mod.main(["--dir", str(tmp_path)]) == 0
    _round(tmp_path, 3, 4000.0)  # 2.25x drop: inside the 3x budget
    assert mod.main(["--dir", str(tmp_path)]) == 0
    capsys.readouterr()
    # a tighter gate catches the same drop
    assert mod.main(["--dir", str(tmp_path), "--threshold", "1.5"]) == 1
    capsys.readouterr()


def test_unparseable_rounds_are_skipped(tmp_path, capsys):
    """A timed-out round (parsed: null — the BENCH_r05 mode) carries no
    rows; the gate compares the last two PARSEABLE rounds instead of
    false-failing."""
    mod = _load()
    _round(tmp_path, 1, 8000.0)
    _round(tmp_path, 2, 9000.0)
    _round(tmp_path, 3, 0.0, parsed=False)
    assert mod.main(["--dir", str(tmp_path)]) == 0
    assert "r01 -> r02" in capsys.readouterr().out


def test_phase_rows_and_time_keys_compare(tmp_path, capsys):
    """New-format documents (bench_emit phases) flatten into gated rows;
    latency keys regress on GROWTH, and timed-out phases are skipped."""
    mod = _load()
    _round(tmp_path, 1, 9000.0, phases={
        "e2e": {"status": "ok", "rows": {"e2e_wire_to_verdict_sets_per_sec": 2000.0}},
        "hasher": {"status": "ok", "rows": {"hasher_1m_one_change_ms": 12.0}},
    })
    _round(tmp_path, 2, 9000.0, phases={
        "e2e": {"status": "ok", "rows": {"e2e_wire_to_verdict_sets_per_sec": 1900.0}},
        "hasher": {"status": "ok", "rows": {"hasher_1m_one_change_ms": 50.0}},
    })
    assert mod.main(["--dir", str(tmp_path)]) == 1  # 12 -> 50 ms: >3x slower
    assert "hasher.hasher_1m_one_change_ms" in capsys.readouterr().out
    # a timed-out NON-required phase in the latest round drops out of the
    # comparison (the REQUIRED e2e row must still be present — it's gated
    # by name; see test_required_key_missing_fails)
    _round(tmp_path, 3, 9000.0, phases={
        "e2e": {"status": "ok", "rows": {"e2e_wire_to_verdict_sets_per_sec": 1850.0}},
        "hasher": {"status": "timeout", "rows": {}},
    })
    assert mod.main(["--dir", str(tmp_path)]) == 0
    capsys.readouterr()


def test_insufficient_history_is_not_a_failure(tmp_path, capsys):
    mod = _load()
    assert mod.main(["--dir", str(tmp_path)]) == 0
    _round(tmp_path, 1, 9000.0)
    assert mod.main(["--dir", str(tmp_path)]) == 0
    assert "nothing to gate" in capsys.readouterr().out


def test_committed_bench_history_passes():
    """The acceptance gate: the repo's own BENCH_r*.json history must be
    green (r05 never parsed and is skipped; r03 -> r04 improved)."""
    mod = _load()
    assert mod.main(["--dir", REPO_ROOT]) == 0


def test_details_file_augments_latest_round(tmp_path, capsys):
    mod = _load()
    # legacy flat rows (rounds <= 5 style) in the prior round
    _round(tmp_path, 1, 9000.0,
           extra={"e2e_wire_to_verdict_sets_per_sec": 2000.0})
    _round(tmp_path, 2, 9000.0)
    details = tmp_path / "bench_details.json"
    # legacy flat details format: rows merge into the latest round
    details.write_text(json.dumps({
        "metric": "bls_signature_sets_verified_per_sec",
        "value": 9000.0,
        "e2e_wire_to_verdict_sets_per_sec": 500.0,  # 4x drop vs r01
    }))
    assert mod.main(["--dir", str(tmp_path), "--details", str(details)]) == 1
    assert "e2e_wire_to_verdict_sets_per_sec" in capsys.readouterr().out


# --- required gated keys (round 6) -------------------------------------------


def test_required_key_gated_across_phase_rename(tmp_path, capsys):
    """The per-set floor moving from a legacy flat key into a phase row
    must STAY gated: base-name matching catches a >3x drop that exact-key
    intersection would silently skip."""
    mod = _load()
    _round(tmp_path, 1, 9000.0,
           extra={"device_sets_per_sec_floor_distinct_pk_and_msg": 3200.0})
    _round(tmp_path, 2, 9000.0, phases={
        "worst_case": {"status": "ok", "rows": {
            "device_sets_per_sec_floor_distinct_pk_and_msg": 800.0,  # 4x drop
        }},
    })
    assert mod.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "device_sets_per_sec_floor_distinct_pk_and_msg" in out


def test_required_key_missing_fails(tmp_path, capsys):
    """A required row present in the prior round but absent from the
    current one fails the gate — a disappeared row hides regressions as
    effectively as a slow one (the BENCH_r05 lesson)."""
    mod = _load()
    _round(tmp_path, 1, 9000.0,
           extra={"e2e_wire_to_verdict_sets_per_sec": 2000.0})
    _round(tmp_path, 2, 9500.0)  # e2e row gone
    assert mod.main(["--dir", str(tmp_path)]) == 1
    assert "missing from current round" in capsys.readouterr().out


def test_degraded_rounds_are_skipped(tmp_path, capsys):
    """A round that ran with CPU fallbacks / open breaker / armed faults
    (supervisor.degraded — round 7) measures the wrong tier: it must be
    skipped with a note, never gated, in EITHER direction — its terrible
    numbers are not a regression, and a later healthy round recovering
    from them is not a 10x win."""
    mod = _load()
    _round(tmp_path, 1, 9000.0)
    _round(tmp_path, 2, 900.0, extra={  # 10x "drop" — but CPU-tier numbers
        "supervisor": {"degraded": True,
                       "fallbacks": {"breaker_open": 41},
                       "breaker_state": 2},
    })
    assert mod.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "DEGRADED" in out and "nothing to gate" in out
    _round(tmp_path, 3, 8800.0)  # healthy again: compared against r01
    assert mod.main(["--dir", str(tmp_path)]) == 0
    assert "r01 -> r03" in capsys.readouterr().out
    # a degraded details file must not augment the latest healthy round
    details = tmp_path / "bench_details.json"
    details.write_text(json.dumps({
        "metric": "bls_signature_sets_verified_per_sec",
        "value": 700.0,
        "supervisor": {"degraded": True},
    }))
    assert mod.main(["--dir", str(tmp_path), "--details", str(details)]) == 0
    capsys.readouterr()
    # a healthy supervisor section (degraded: false) still gates normally
    _round(tmp_path, 4, 2000.0, extra={  # 4.4x real drop, not degraded
        "supervisor": {"degraded": False, "breaker_state": 0},
    })
    assert mod.main(["--dir", str(tmp_path)]) == 1
    capsys.readouterr()


def test_required_key_improvement_passes(tmp_path, capsys):
    """The round-6 re-bind (e2e_wire_to_verdict now the device-decompress
    default path, ~6x faster) is an IMPROVEMENT and must pass."""
    mod = _load()
    _round(tmp_path, 1, 9000.0,
           extra={"e2e_wire_to_verdict_sets_per_sec": 2042.0})
    _round(tmp_path, 2, 9000.0, phases={
        "e2e": {"status": "ok", "rows": {
            "e2e_wire_to_verdict_sets_per_sec": 12039.0,
        }},
    })
    assert mod.main(["--dir", str(tmp_path)]) == 0
    capsys.readouterr()


# --- timed-out partial flushes (round 7) -------------------------------------


def test_timed_out_rounds_are_skipped_but_logged(tmp_path, capsys):
    """A round the watchdog flushed mid-run (`timed_out: true`, round 7)
    is parseable JSON with real-looking rows — but its rates stopped at
    the deadline. Skippable-but-logged, in either direction: truncated
    numbers gate nothing, and recovery from them is not a win."""
    mod = _load()
    _round(tmp_path, 1, 9000.0)
    _round(tmp_path, 2, 1200.0, extra={  # "7.5x drop" — but partial
        "timed_out": True, "watchdog_fired_after_s": 780.0,
    })
    assert mod.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "timed out mid-run" in out and "nothing to gate" in out
    _round(tmp_path, 3, 8800.0)  # completed again: compared against r01
    assert mod.main(["--dir", str(tmp_path)]) == 0
    assert "r01 -> r03" in capsys.readouterr().out


def test_timed_out_details_do_not_augment(tmp_path, capsys):
    """A timed-out bench_details.json (SIGTERM flush) must not graft its
    partial per-phase rows onto the latest completed round."""
    mod = _load()
    _round(tmp_path, 1, 9000.0,
           extra={"e2e_wire_to_verdict_sets_per_sec": 2000.0})
    _round(tmp_path, 2, 9000.0,
           extra={"e2e_wire_to_verdict_sets_per_sec": 1900.0})
    details = tmp_path / "bench_details.json"
    details.write_text(json.dumps({
        "metric": "bls_signature_sets_verified_per_sec",
        "value": 9000.0,
        "timed_out": True,
        "e2e_wire_to_verdict_sets_per_sec": 300.0,  # partial-run rate
    }))
    assert mod.main(["--dir", str(tmp_path), "--details", str(details)]) == 0
    capsys.readouterr()


def test_empty_history_dir_exits_zero(tmp_path, capsys):
    """A directory with no BENCH files at all (fresh checkout) is a clean
    exit-0 'nothing to gate' — never a traceback."""
    mod = _load()
    assert mod.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "no parseable bench history" in out


def test_null_round_file_is_skipped_not_crashed(tmp_path, capsys):
    """A round file containing JSON `null` (a harness that died while
    writing) must be a logged skip, not an AttributeError."""
    mod = _load()
    (tmp_path / "BENCH_r01.json").write_text("null")
    assert mod.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "not a JSON object" in out
    assert "nothing to gate" in out


def test_non_dict_parsed_is_skipped_not_crashed(tmp_path, capsys):
    """`parsed` holding a string/list (a corrupted emitter document) must
    be a logged skip, not a crash in the timed_out/degraded probes."""
    mod = _load()
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"n": 1, "parsed": "watchdog killed mid-write"})
    )
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"n": 2, "parsed": [1, 2, 3]})
    )
    assert mod.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "`parsed` is not a JSON object" in out


def test_truncated_round_file_is_logged_skip(tmp_path, capsys):
    """Half-written JSON (disk full / kill -9) is an unreadable-file skip
    with the parse error in the note."""
    mod = _load()
    (tmp_path / "BENCH_r01.json").write_text('{"n": 1, "parsed": {')
    (tmp_path / "BENCH_r02.json").write_text("")
    assert mod.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert out.count("unreadable round file") == 2


def test_single_parseable_round_exits_zero(tmp_path, capsys):
    mod = _load()
    _round(tmp_path, 1, 9000.0)
    (tmp_path / "BENCH_r02.json").write_text("null")
    assert mod.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "1 parseable round" in out


# --- SLO verdict gating (ISSUE 16) -------------------------------------------


def _slo(*states):
    """An `slo` bench section with objectives o0..oN in the given states."""
    return {"slo": {"objectives": [
        {"name": f"o{i}", "state": s} for i, s in enumerate(states)
    ]}}


def test_burning_objective_fails_gate_by_name(tmp_path, capsys):
    mod = _load()
    _round(tmp_path, 1, 9000.0, extra=_slo("ok", "ok"))
    _round(tmp_path, 2, 9100.0, extra=_slo("ok", "burning"))
    assert mod.main(["--dir", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    # the verdict delta is printed per objective, and the failure names
    # the burning objective rather than a raw-number diff
    assert "slo:o1  ok -> burning" in out
    assert "BURNING" in out
    assert "slo:o1 (error budget burning)" in out
    assert "error budget" in out.split("FAIL:")[1]


def test_slo_ok_rounds_print_deltas_and_pass(tmp_path, capsys):
    mod = _load()
    _round(tmp_path, 1, 9000.0, extra=_slo("burning", "ok"))
    _round(tmp_path, 2, 9100.0, extra=_slo("ok", "ok"))  # recovered
    assert mod.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "slo:o0  burning -> ok" in out
    assert "OK: no gated key regressed past the threshold" in out


def test_slo_only_mode_gates_exclusively_on_verdicts(tmp_path, capsys):
    mod = _load()
    _round(tmp_path, 1, 9000.0, extra=_slo("ok"))
    _round(tmp_path, 2, 1000.0, extra=_slo("ok"))  # 9x numeric drop
    # the numeric gate fails this history...
    assert mod.main(["--dir", str(tmp_path)]) == 1
    capsys.readouterr()
    # ...but --slo-only judges only the budgets
    assert mod.main(["--dir", str(tmp_path), "--slo-only"]) == 0
    out = capsys.readouterr().out
    assert "numeric thresholds skipped" in out
    assert "OK: no SLO objective is burning its error budget" in out
    _round(tmp_path, 3, 9000.0, extra=_slo("burning"))
    assert mod.main(["--dir", str(tmp_path), "--slo-only"]) == 1
    capsys.readouterr()


def test_rounds_predating_slo_engine_never_gate(tmp_path, capsys):
    """Committed history predates the engine: no `slo` section means no
    verdicts and no gating — in both modes."""
    mod = _load()
    _round(tmp_path, 1, 9000.0)
    _round(tmp_path, 2, 9100.0)
    assert mod.main(["--dir", str(tmp_path)]) == 0
    assert "no SLO verdicts in either round" in capsys.readouterr().out
    assert mod.main(["--dir", str(tmp_path), "--slo-only"]) == 0
    capsys.readouterr()


def test_degraded_and_timed_out_rounds_report_burn_state(tmp_path, capsys):
    """ISSUE 16 satellite: a skipped round still says what its budgets
    looked like when it died (the skip notes themselves are unchanged)."""
    mod = _load()
    _round(tmp_path, 1, 9000.0, extra=_slo("ok"))
    _round(tmp_path, 2, 900.0, extra={
        "supervisor": {"degraded": True, "breaker_state": 2},
        **_slo("burning", "ok"),
    })
    _round(tmp_path, 3, 1200.0, extra={
        "timed_out": True, **_slo("ok", "ok"),
    })
    _round(tmp_path, 4, 8800.0, extra=_slo("ok"))
    assert mod.main(["--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "DEGRADED" in out and "timed out mid-run" in out
    assert "r02 burn state — BURNING: o0" in out
    assert "r03 burn state — all 2 objectives ok" in out
    # a skipped round with no slo section reports n/a, not a crash
    _round(tmp_path, 5, 1000.0, extra={"timed_out": True})
    _round(tmp_path, 6, 8700.0, extra=_slo("ok"))
    assert mod.main(["--dir", str(tmp_path)]) == 0
    assert "r05 burn state — n/a" in capsys.readouterr().out


def test_slo_from_details_augments_latest_round(tmp_path, capsys):
    """bench_details.json carries the slo section for the newest round
    when the driver's BENCH_r file predates the engine's emission."""
    mod = _load()
    _round(tmp_path, 1, 9000.0)
    _round(tmp_path, 2, 9100.0)
    details = tmp_path / "bench_details.json"
    details.write_text(json.dumps({
        "metric": "bls_signature_sets_verified_per_sec",
        "value": 9100.0,
        **_slo("ok", "burning"),
    }))
    assert mod.main(["--dir", str(tmp_path), "--details", str(details)]) == 1
    out = capsys.readouterr().out
    assert "slo:o1  n/a -> burning" in out
