"""State-transition tests: shuffle, genesis, sanity slots/blocks, finality.

Models the reference's spec-test categories (sanity, finality — SURVEY.md
§4.2) as self-contained scenarios on the minimal preset: a 64-validator
interop genesis driven through 4 epochs of fully-attested blocks must
justify and finalize; signature sets of produced blocks must verify.
"""

import numpy as np
import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig, compute_signing_root
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.params import DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER, DOMAIN_RANDAO
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import (
    CachedBeaconState,
    interop_genesis_state,
    process_slots,
    state_transition,
)
from lodestar_tpu.state_transition import util
from lodestar_tpu.state_transition.block import _epoch_signing_root
from lodestar_tpu.state_transition.genesis import is_valid_genesis_state
from lodestar_tpu.state_transition.signature_sets import get_block_signature_sets
from lodestar_tpu.types import get_types

N_VALIDATORS = 64
SPE = MINIMAL.SLOTS_PER_EPOCH


@pytest.fixture(scope="module")
def types():
    return get_types(MINIMAL).phase0


@pytest.fixture(scope="module")
def genesis(types):
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, N_VALIDATORS, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    return config, state


def test_shuffle_list_matches_per_index():
    seed = b"\x5a" * 32
    n = 100
    idx = np.arange(n, dtype=np.int64)
    shuffled = util.shuffle_list(idx, seed, MINIMAL.SHUFFLE_ROUND_COUNT)
    expected = [
        util.compute_shuffled_index(i, n, seed, MINIMAL.SHUFFLE_ROUND_COUNT)
        for i in range(n)
    ]
    assert shuffled.tolist() == expected
    inv = util.unshuffle_list(shuffled, seed, MINIMAL.SHUFFLE_ROUND_COUNT)
    assert inv.tolist() == idx.tolist()


def test_interop_genesis_valid(genesis):
    config, state = genesis
    assert is_valid_genesis_state(config, state)
    assert len(state.validators) == N_VALIDATORS
    assert all(v.activation_epoch == 0 for v in state.validators)
    assert state.balances == [MINIMAL.MAX_EFFECTIVE_BALANCE] * N_VALIDATORS


def test_process_slots_across_epoch(genesis, types):
    config, state = genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    process_slots(cached, types, SPE + 1)
    assert cached.state.slot == SPE + 1
    assert cached.current_epoch == 1


# --- mini validator/producer (the test-side analog of the reference's
# valid-data factories, beacon-node/test/utils/validationData) -------------


def _sk(i: int):
    return bls.interop_secret_key(i)


def _block_root_at(state, slot: int) -> bytes:
    if slot == state.slot:
        hdr = state.latest_block_header.copy()
        if hdr.state_root == b"\x00" * 32:
            hdr.state_root = state.hash_tree_root()
        return hdr.hash_tree_root()
    return bytes(state.block_roots[slot % MINIMAL.SLOTS_PER_HISTORICAL_ROOT])


def produce_attestations(config, types, cached, head_root: bytes):
    """Full-participation attestations for the current slot."""
    state = cached.state
    slot = state.slot
    epoch = slot // SPE
    start = epoch * SPE
    target_root = head_root if start == slot else _block_root_at(state, start)
    atts = []
    domain = config.get_domain(DOMAIN_BEACON_ATTESTER, slot, epoch)
    for index in range(cached.epoch_ctx.get_committee_count_per_slot(epoch)):
        committee = cached.epoch_ctx.get_beacon_committee(slot, index)
        data = types.AttestationData(
            slot=slot,
            index=index,
            beacon_block_root=head_root,
            source=state.current_justified_checkpoint.copy(),
            target=types.Checkpoint(epoch=epoch, root=target_root),
        )
        root = compute_signing_root(data.hash_tree_root(), domain)
        sigs = [_sk(int(v)).sign(root) for v in committee]
        atts.append(
            types.Attestation(
                aggregation_bits=[True] * len(committee),
                data=data,
                signature=bls.aggregate_signatures(sigs).to_bytes(),
            )
        )
    return atts


def produce_block(config, types, cached, slot: int, attestations):
    pre = cached.copy()
    if slot > pre.state.slot:
        process_slots(pre, types, slot)
    proposer = pre.epoch_ctx.get_beacon_proposer(slot)
    sk = _sk(proposer)
    randao_domain = config.get_domain(DOMAIN_RANDAO, slot)
    body = types.BeaconBlockBody(
        randao_reveal=sk.sign(
            _epoch_signing_root(slot // SPE, randao_domain)
        ).to_bytes(),
        eth1_data=pre.state.eth1_data.copy(),
        attestations=attestations,
    )
    block = types.BeaconBlock(
        slot=slot,
        proposer_index=proposer,
        parent_root=pre.state.latest_block_header.hash_tree_root(),
        state_root=b"\x00" * 32,
        body=body,
    )
    # compute post-state root
    trial = pre.copy()
    state_transition(
        trial,
        types,
        types.SignedBeaconBlock(message=block.copy(), signature=b"\x00" * 96),
        verify_state_root=False,
        verify_signatures=False,
    )
    block.state_root = trial.state.hash_tree_root()
    domain = config.get_domain(DOMAIN_BEACON_PROPOSER, slot)
    sig = sk.sign(compute_signing_root(block.hash_tree_root(), domain))
    return types.SignedBeaconBlock(message=block, signature=sig.to_bytes())


@pytest.fixture(scope="module")
def finality_run(genesis, types):
    """Drive 4 epochs of fully-attested blocks; collect artifacts."""
    config, state = genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    pending = []
    blocks = []
    for slot in range(1, 4 * SPE + 1):
        signed = produce_block(config, types, cached, slot, pending)
        state_transition(
            cached, types, signed, verify_state_root=True, verify_signatures=False
        )
        blocks.append(signed)
        head_root = signed.message.hash_tree_root()
        pending = produce_attestations(config, types, cached, head_root)
    return config, cached, blocks


def test_finality_advances(finality_run):
    _, cached, _ = finality_run
    assert cached.current_epoch == 4
    assert cached.state.current_justified_checkpoint.epoch >= 2
    assert cached.state.finalized_checkpoint.epoch >= 1


def test_balances_accrue_rewards(finality_run):
    _, cached, _ = finality_run
    # perfect participation, no leak: every validator should be at or above
    # its starting balance after reward epochs
    assert min(cached.state.balances) >= MINIMAL.MAX_EFFECTIVE_BALANCE


def test_block_signature_sets_verify(finality_run, genesis, types):
    config, _, blocks = finality_run
    _, state = genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    # replay to just before the chosen block, then extract + verify its sets
    target = blocks[SPE]  # first block of epoch 1 (carries attestations)
    for signed in blocks[: SPE]:
        state_transition(
            cached, types, signed, verify_state_root=False, verify_signatures=False
        )
    if target.message.slot > cached.state.slot + 1:
        process_slots(cached, types, target.message.slot)
    sets = get_block_signature_sets(cached, types, target)
    assert len(sets) >= 2  # proposer + randao at minimum
    assert bls.verify_signature_sets(sets)

    # a corrupted proposer signature must fail the batch
    bad = types.SignedBeaconBlock(
        message=target.message.copy(), signature=b"\x11" * 96
    )
    bad_sets = get_block_signature_sets(cached, types, bad)
    assert not bls.verify_signature_sets(bad_sets)


def test_full_signature_verification_one_block(finality_run, genesis, types):
    config, _, blocks = finality_run
    _, state = genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    for signed in blocks[:2]:
        state_transition(
            cached, types, signed, verify_state_root=True, verify_signatures=True
        )
    assert cached.state.slot == 2
