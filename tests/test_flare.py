"""Flare debug CLI: self-slashing submission against a live node.

Reference analog: `packages/flare` `self-slash-proposer` /
`self-slash-attester` commands submitting crafted slashings over the
Beacon API.
"""

import pytest

from lodestar_tpu.cli.__main__ import main as cli_main
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.node.node import BeaconNode, NodeOptions
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.types import get_types


@pytest.fixture(scope="module")
def node_env():
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, 16, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    node = BeaconNode.init(
        config, types, state.copy(), NodeOptions(rest=True, rest_port=0)
    )
    yield config, types, node
    node.close()


def test_flare_self_slash_proposer(node_env):
    config, types, node = node_env
    rc = cli_main(
        [
            "flare", "self-slash-proposer",
            "--server", f"127.0.0.1:{node.api_server.port}",
            "--validators", "0..2",
            "--slot", "1",
        ]
    )
    assert rc == 0
    pool = node.chain.op_pool
    assert set(pool.proposer_slashings) >= {0, 1}
    # the two headers are genuinely conflicting: same slot, different roots
    slashing = pool.proposer_slashings[0]
    h1, h2 = slashing.signed_header_1.message, slashing.signed_header_2.message
    assert int(h1.slot) == int(h2.slot) == 1
    assert h1.hash_tree_root() != h2.hash_tree_root()


def test_flare_self_slash_attester(node_env):
    config, types, node = node_env
    rc = cli_main(
        [
            "flare", "self-slash-attester",
            "--server", f"127.0.0.1:{node.api_server.port}",
            "--validators", "2,3,4",
            "--slot", "1",
            "--batch-size", "2",
        ]
    )
    assert rc == 0
    pool = node.chain.op_pool
    assert len(pool.attester_slashings) == 2  # batches of 2 then 1
    from lodestar_tpu.state_transition.block import is_slashable_attestation_data

    for slashing in pool.attester_slashings:
        assert is_slashable_attestation_data(
            slashing.attestation_1.data, slashing.attestation_2.data
        )
    covered = {
        int(i)
        for s in pool.attester_slashings
        for i in s.attestation_1.attesting_indices
    }
    assert covered == {2, 3, 4}


def test_flare_pool_routes_roundtrip(node_env):
    """The GET pool routes serve what flare submitted."""
    from lodestar_tpu.api.client import BeaconApiClient

    config, types, node = node_env
    client = BeaconApiClient("127.0.0.1", node.api_server.port)
    props = client.getPoolProposerSlashings()
    attrs = client.getPoolAttesterSlashings()
    assert len(props) >= 2
    assert len(attrs) == 2
    restored = types.ProposerSlashing.from_obj(props[0])
    assert int(restored.signed_header_1.message.slot) == 1
