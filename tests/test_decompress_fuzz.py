"""Malformed-encoding differential fuzz across the three decompress
tiers (ISSUE 15 satellite): for every hostile byte pattern the CPU
oracle, the single-device raw kernel, and the NEW sharded-raw twin must
agree on the batch verdict — bit-identical, same random coefficients.

Corpus: bad flag bits (compression cleared), wrong y sign, x ≥ p,
off-curve / non-residue x, infinity-with-payload, and a valid-encoding
point outside the G2 subgroup (caught only by the plane check).

COMPILE DISCIPLINE: ONE grouped shape (8 rows × 4 lanes) shared by every
scenario — two deep compiles total (single-device grouped-raw kernel +
the 8-chip sharded grouped-raw twin), everything after is dispatch-only.
"""

import numpy as np
import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.bls.curve import B2, PointG2, g2_from_bytes, g2_to_bytes
from lodestar_tpu.bls.fields import P, Fq2
from lodestar_tpu.chain.bls_verifier import CpuBlsVerifier
from lodestar_tpu.parallel.verifier import TpuBlsVerifier, _rand_pairs

# deep-kernel compiles (decompress embeds 380-step pow scans): slow tier
pytestmark = pytest.mark.slow

_COUNTER = [0]


def _det_rng():
    _COUNTER[0] += 1
    return (0x9E3779B97F4A7C15 * _COUNTER[0]) & ((1 << 64) - 1)


ROWS, LANES = 8, 2  # 8 shared roots × 2 signers → 8×4 grouped plan


def _make_sets():
    """8 committees × 2 signers, shared root per committee — groups into
    the module's single 8-row plan."""
    sets = []
    for row in range(ROWS):
        root = bytes([row ^ 0x5A]) * 32
        for j in range(LANES):
            sk = bls.interop_secret_key(row * LANES + j)
            sets.append(
                bls.SignatureSet(
                    pubkey=sk.to_public_key(),
                    message=root,
                    signature=sk.sign(root).to_bytes(),
                )
            )
    return sets


def _non_subgroup_point() -> PointG2:
    x = Fq2.from_ints(5, 1)
    while True:
        y2 = x * x * x + B2
        y = y2.sqrt()
        if y is not None:
            pt = PointG2(x, y, Fq2.one())
            if not pt.is_in_subgroup():
                return pt
        x = x + Fq2.from_ints(1, 0)


def _clear_compression(b: bytes) -> bytes:
    raw = bytearray(b)
    raw[0] &= 0x7F
    return bytes(raw)


def _flip_y_sign(b: bytes) -> bytes:
    raw = bytearray(b)
    raw[0] ^= 0x20
    return bytes(raw)


def _x_ge_p(b: bytes) -> bytes:
    raw = bytearray(b)
    pb = bytearray(P.to_bytes(48, "big"))
    pb[0] |= 0x80 | (raw[0] & 0x20)  # x_c1 = p, flags preserved
    raw[:48] = pb
    return bytes(raw)


def _infinity_with_payload(_b: bytes) -> bytes:
    return bytes([0xC0, 0x01]) + b"\x00" * 94


def _off_curve(b: bytes) -> bytes:
    """Walk the last x byte until the oracle refuses to decompress —
    either y² = x³ + 4(1+u) has no root (non-residue) or the point is
    otherwise unparseable."""
    raw = bytearray(b)
    while True:
        raw[95] = (raw[95] + 1) % 256
        try:
            g2_from_bytes(bytes(raw))
        except Exception:
            return bytes(raw)


def _non_subgroup(_b: bytes) -> bytes:
    return g2_to_bytes(_non_subgroup_point())


CORPUS = [
    ("clear_compression_flag", _clear_compression),
    ("wrong_y_sign", _flip_y_sign),
    ("x_ge_p", _x_ge_p),
    ("infinity_with_payload", _infinity_with_payload),
    ("off_curve_non_residue", _off_curve),
    ("non_subgroup_point", _non_subgroup),
]


@pytest.fixture(scope="module")
def host():
    """Single-device raw verifier: marshal (zero-copy signature bytes) +
    the unsharded grouped-raw parity kernel."""
    return TpuBlsVerifier(
        buckets=(16,), grouped_configs=((ROWS, 4),), rng=_det_rng,
        device_decompress=True,
    )


@pytest.fixture(scope="module")
def sharded_raw(cpu_mesh):
    from lodestar_tpu.parallel.sharded import ShardedGroupedRawVerifier

    return ShardedGroupedRawVerifier(cpu_mesh)


@pytest.fixture(scope="module")
def cpu_oracle():
    return CpuBlsVerifier()


def _verdicts(host, sharded_raw, cpu_oracle, sets):
    """(cpu, single_device_raw, sharded_raw) verdicts for one batch, the
    device pair sharing one set of random coefficients."""
    cpu = cpu_oracle.verify_signature_sets(sets)
    plan = host._plan_groups(sets)
    assert plan is not None, "corpus must keep its grouped shape"
    marshalled = host._marshal_grouped(sets, plan, raw=True)
    assert marshalled is not None
    g, sig_raw = marshalled
    a_bits, b_bits = _rand_pairs(g.valid.shape, host._rng)
    single = bool(host.kernels.verify_grouped_raw(g, sig_raw, a_bits, b_bits))
    sharded = bool(sharded_raw.submit(g, sig_raw, a_bits, b_bits))
    return cpu, single, sharded


def test_valid_baseline_all_tiers_accept(host, sharded_raw, cpu_oracle):
    cpu, single, sharded = _verdicts(host, sharded_raw, cpu_oracle, _make_sets())
    assert (cpu, single, sharded) == (True, True, True)


@pytest.mark.parametrize("name,mutate", CORPUS)
@pytest.mark.parametrize("target", [0, ROWS * LANES - 1])
def test_malformed_encoding_differential(
    host, sharded_raw, cpu_oracle, name, mutate, target
):
    """Every hostile pattern — injected at the first and the last lane so
    it lands on the first and the last CHIP of the sharded grid — must be
    rejected identically by all three tiers."""
    sets = _make_sets()
    sets[target] = bls.SignatureSet(
        pubkey=sets[target].pubkey,
        message=sets[target].message,
        signature=mutate(sets[target].signature),
    )
    cpu, single, sharded = _verdicts(host, sharded_raw, cpu_oracle, sets)
    assert cpu is False, name
    assert single == cpu, name
    assert sharded == single, name
