"""bellatrix + capella state-transition tests: scheduled fork upgrades in
process_slots, execution payload processing, withdrawals sweep, BLS→execution
credential changes (reference analog: bellatrix/capella sanity + transition
spec suites)."""

import dataclasses

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.config.beacon_config import BeaconConfig, compute_signing_root
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.params import (
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    ETH1_ADDRESS_WITHDRAWAL_PREFIX,
    ForkName,
)
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import (
    CachedBeaconState,
    interop_genesis_state,
    process_slots,
    state_transition,
)
from lodestar_tpu.state_transition.altair import upgrade_state_to_altair
from lodestar_tpu.state_transition.bellatrix import (
    is_execution_enabled,
    is_merge_transition_complete,
)
from lodestar_tpu.state_transition.block import _epoch_signing_root
from lodestar_tpu.state_transition.capella import (
    get_expected_withdrawals,
    process_bls_to_execution_change,
)
from lodestar_tpu.state_transition.signature_sets import get_block_signature_sets
from lodestar_tpu.chain.bls_verifier import CpuBlsVerifier
from lodestar_tpu.types import get_types

N = 16
SPE = MINIMAL.SLOTS_PER_EPOCH

SCHEDULED = dataclasses.replace(
    MINIMAL_CHAIN_CONFIG,
    ALTAIR_FORK_EPOCH=0,
    BELLATRIX_FORK_EPOCH=1,
    CAPELLA_FORK_EPOCH=2,
)


def _sk(i):
    return bls.interop_secret_key(i)


@pytest.fixture(scope="module")
def scheduled_genesis():
    """Altair genesis under a schedule that forks to bellatrix at epoch 1
    and capella at epoch 2."""
    t = get_types(MINIMAL)
    from lodestar_tpu.config.beacon_config import ChainForkConfig

    fork_config = ChainForkConfig(SCHEDULED, MINIMAL)
    pre = interop_genesis_state(fork_config, t.phase0, N, genesis_time=1_600_000_000)
    config = BeaconConfig(SCHEDULED, bytes(pre.genesis_validators_root), MINIMAL)
    state = upgrade_state_to_altair(config, MINIMAL, pre, t.altair)
    return config, t, state


def test_scheduled_upgrades_in_process_slots(scheduled_genesis):
    config, t, state = scheduled_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    assert cached.fork == ForkName.altair

    process_slots(cached, t.altair, SPE)  # enter epoch 1 → bellatrix
    assert cached.fork == ForkName.bellatrix
    assert bytes(cached.state.fork.current_version) == config.BELLATRIX_FORK_VERSION
    assert not is_merge_transition_complete(cached.state)

    process_slots(cached, t.bellatrix, 2 * SPE)  # enter epoch 2 → capella
    assert cached.fork == ForkName.capella
    assert bytes(cached.state.fork.current_version) == config.CAPELLA_FORK_VERSION
    assert cached.state.next_withdrawal_index == 0
    assert cached.state.next_withdrawal_validator_index == 0
    assert len(cached.state.historical_summaries) == 0
    # participation flags survived both upgrades
    assert len(cached.state.previous_epoch_participation) == N


def _produce_block(config, types, cached, slot, payload=None, changes=()):
    """Minimal valid block at `slot` (no attestations; optional payload)."""
    pre = cached.copy()
    if slot > pre.state.slot:
        process_slots(pre, types, slot)
    types = get_types(MINIMAL).by_fork[pre.fork]
    proposer = pre.epoch_ctx.get_beacon_proposer(slot)
    sk = _sk(proposer)
    body = types.BeaconBlockBody(
        randao_reveal=sk.sign(
            _epoch_signing_root(slot // SPE, config.get_domain(DOMAIN_RANDAO, slot))
        ).to_bytes(),
        eth1_data=pre.state.eth1_data.copy(),
    )
    if hasattr(body, "sync_aggregate"):
        body.sync_aggregate = types.SyncAggregate(
            sync_committee_bits=[False] * MINIMAL.SYNC_COMMITTEE_SIZE,
            sync_committee_signature=b"\xc0" + b"\x00" * 95,
        )
    if payload is not None:
        body.execution_payload = payload
    if changes:
        body.bls_to_execution_changes = list(changes)
    block = types.BeaconBlock(
        slot=slot,
        proposer_index=proposer,
        parent_root=pre.state.latest_block_header.hash_tree_root(),
        state_root=b"\x00" * 32,
        body=body,
    )
    trial = pre.copy()
    state_transition(
        trial,
        types,
        types.SignedBeaconBlock(message=block.copy(), signature=b"\x00" * 96),
        verify_state_root=False,
        verify_signatures=False,
    )
    block.state_root = trial.state.hash_tree_root()
    domain = config.get_domain(DOMAIN_BEACON_PROPOSER, slot)
    sig = sk.sign(compute_signing_root(block.hash_tree_root(), domain))
    return types.SignedBeaconBlock(message=block, signature=sig.to_bytes())


def test_bellatrix_pre_merge_blocks(scheduled_genesis):
    """Pre-merge bellatrix blocks carry default payloads; execution is
    disabled until a non-default payload lands."""
    config, t, state = scheduled_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    process_slots(cached, t.altair, SPE)
    signed = _produce_block(config, t.bellatrix, cached, SPE + 1)
    assert not is_execution_enabled(cached.state, signed.message.body)
    state_transition(cached, t.bellatrix, signed, verify_signatures=True)
    assert cached.state.slot == SPE + 1


def _merge_payload(types, cached, config):
    """A structurally valid merge-transition payload for the next slot."""
    from lodestar_tpu.state_transition.bellatrix import (
        compute_timestamp_at_slot,
        get_randao_mix,
    )

    state = cached.state
    return types.ExecutionPayload(
        parent_hash=b"\x11" * 32,
        fee_recipient=b"\x22" * 20,
        state_root=b"\x33" * 32,
        receipts_root=b"\x44" * 32,
        prev_randao=get_randao_mix(state, cached.current_epoch, cached.preset),
        block_number=1,
        gas_limit=30_000_000,
        gas_used=21_000,
        timestamp=compute_timestamp_at_slot(config, state),
        base_fee_per_gas=7,
        block_hash=b"\x55" * 32,
        transactions=[b"\x01\x02"],
    )


def test_bellatrix_merge_transition_block(scheduled_genesis):
    config, t, state = scheduled_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    process_slots(cached, t.altair, SPE + 1)

    payload = _merge_payload(t.bellatrix, cached, config)
    # build by hand at the current slot (payload fields depend on post-slot
    # state, so _produce_block's process_slots path would skew timestamp)
    signed = _produce_block(config, t.bellatrix, cached, SPE + 1, payload=payload)
    assert is_execution_enabled(cached.state, signed.message.body)
    state_transition(cached, t.bellatrix, signed, verify_signatures=True)
    assert is_merge_transition_complete(cached.state)
    hdr = cached.state.latest_execution_payload_header
    assert bytes(hdr.block_hash) == b"\x55" * 32
    assert hdr.block_number == 1


def test_capella_withdrawals_sweep(scheduled_genesis):
    config, t, state = scheduled_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    process_slots(cached, t.altair, 2 * SPE)
    assert cached.fork == ForkName.capella
    state = cached.state

    # validator 0: fully withdrawable (eth1 creds, withdrawable now, has balance)
    state.validators[0].withdrawal_credentials = (
        ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\xaa" * 20
    )
    state.validators[0].withdrawable_epoch = 0
    # validator 1: partially withdrawable (max effective, excess balance)
    state.validators[1].withdrawal_credentials = (
        ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\xbb" * 20
    )
    state.balances[1] = MINIMAL.MAX_EFFECTIVE_BALANCE + 123
    cached.reload_state(state)

    ws = get_expected_withdrawals(cached, t.capella)
    by_validator = {w.validator_index: w for w in ws}
    assert 0 in by_validator and by_validator[0].amount == int(
        cached.flat.balances[0]
    )
    assert 1 in by_validator and by_validator[1].amount == 123
    assert bytes(by_validator[0].address) == b"\xaa" * 20


def test_capella_bls_to_execution_change(scheduled_genesis):
    config, t, state = scheduled_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    process_slots(cached, t.altair, 2 * SPE)

    idx = 3
    sk = _sk(idx)  # interop: withdrawal key == signing key
    change = t.capella.BLSToExecutionChange(
        validator_index=idx,
        from_bls_pubkey=sk.to_public_key().to_bytes(),
        to_execution_address=b"\xcc" * 20,
    )
    from lodestar_tpu.state_transition.capella import (
        bls_to_execution_change_signing_root,
    )

    root = bls_to_execution_change_signing_root(config, cached.state, change)
    signed_change = t.capella.SignedBLSToExecutionChange(
        message=change, signature=sk.sign(root).to_bytes()
    )
    process_bls_to_execution_change(cached, signed_change, verify_signatures=True)
    wc = bytes(cached.state.validators[idx].withdrawal_credentials)
    assert wc[:1] == ETH1_ADDRESS_WITHDRAWAL_PREFIX
    assert wc[12:] == b"\xcc" * 20

    # wrong signature rejected
    bad = t.capella.SignedBLSToExecutionChange(
        message=t.capella.BLSToExecutionChange(
            validator_index=4,
            from_bls_pubkey=_sk(4).to_public_key().to_bytes(),
            to_execution_address=b"\xdd" * 20,
        ),
        signature=sk.sign(root).to_bytes(),
    )
    with pytest.raises(Exception):
        process_bls_to_execution_change(cached, bad, verify_signatures=True)


def test_capella_block_with_change_signature_sets(scheduled_genesis):
    """A capella block carrying a credential change: its signature set is
    extracted and the whole block batch-verifies."""
    config, t, state = scheduled_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    process_slots(cached, t.altair, 2 * SPE)

    idx = 5
    sk = _sk(idx)
    change = t.capella.BLSToExecutionChange(
        validator_index=idx,
        from_bls_pubkey=sk.to_public_key().to_bytes(),
        to_execution_address=b"\xee" * 20,
    )
    from lodestar_tpu.state_transition.capella import (
        bls_to_execution_change_signing_root,
    )

    signed_change = t.capella.SignedBLSToExecutionChange(
        message=change,
        signature=sk.sign(
            bls_to_execution_change_signing_root(config, cached.state, change)
        ).to_bytes(),
    )
    signed = _produce_block(
        config, t.capella, cached, 2 * SPE + 1, changes=[signed_change]
    )
    post = cached.copy()
    state_transition(post, t.capella, signed, verify_signatures=False)
    sets = get_block_signature_sets(post, t.capella, signed)
    # proposer + randao + the credential change
    assert len(sets) == 3
    assert CpuBlsVerifier().verify_signature_sets(sets)
    wc = bytes(post.state.validators[idx].withdrawal_credentials)
    assert wc[12:] == b"\xee" * 20


def test_capella_finality(scheduled_genesis):
    """Chain across both fork boundaries to epoch 4 with empty blocks: the
    transition machinery stays consistent across upgrades (roots verified
    every block)."""
    config, t, state = scheduled_genesis
    cached = CachedBeaconState(config, state.copy(), MINIMAL)
    for slot in range(1, 3 * SPE + 1):
        signed = _produce_block(config, t.altair, cached, slot)
        state_transition(
            cached, t.altair, signed, verify_state_root=True, verify_signatures=False
        )
    assert cached.fork == ForkName.capella
    assert cached.state.slot == 3 * SPE
