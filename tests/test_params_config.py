"""Unit tests for params + config layers (reference: packages/params/test,
packages/config/test)."""

from lodestar_tpu.config import (
    MAINNET_CHAIN_CONFIG,
    NETWORK_CONFIGS,
    BeaconConfig,
    ChainForkConfig,
    compute_domain,
    compute_fork_digest,
)
from lodestar_tpu.params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    FAR_FUTURE_EPOCH,
    MAINNET,
    MINIMAL,
    ForkName,
    ForkSeq,
)


def test_preset_values():
    assert MAINNET.SLOTS_PER_EPOCH == 32
    assert MAINNET.SHUFFLE_ROUND_COUNT == 90
    assert MAINNET.SYNC_COMMITTEE_SIZE == 512
    assert MINIMAL.SLOTS_PER_EPOCH == 8
    assert MINIMAL.SHUFFLE_ROUND_COUNT == 10
    assert MAINNET.SYNC_COMMITTEE_SUBNET_SIZE == 128


def test_fork_order():
    assert ForkSeq[ForkName.phase0] < ForkSeq[ForkName.altair] < ForkSeq[ForkName.bellatrix]


def test_fork_schedule_mainnet():
    cfg = ChainForkConfig(MAINNET_CHAIN_CONFIG)
    assert cfg.get_fork_name_at_epoch(0) == ForkName.phase0
    assert cfg.get_fork_name_at_epoch(74239) == ForkName.phase0
    assert cfg.get_fork_name_at_epoch(74240) == ForkName.altair
    assert cfg.get_fork_name_at_epoch(144896) == ForkName.bellatrix
    assert cfg.get_fork_name_at_epoch(194048) == ForkName.capella
    assert cfg.get_fork_name_at_slot(74240 * 32) == ForkName.altair
    # attribute fall-through: preset and chain config both reachable
    assert cfg.SLOTS_PER_EPOCH == 32
    assert cfg.SECONDS_PER_SLOT == 12


def test_fork_schedule_dev_all_at_genesis():
    cfg = ChainForkConfig(NETWORK_CONFIGS["dev"])
    assert cfg.get_fork_name_at_epoch(0) == ForkName.capella


def test_domain_computation_deterministic():
    gvr = b"\x2a" * 32
    cfg = BeaconConfig(MAINNET_CHAIN_CONFIG, gvr)
    d1 = cfg.get_domain(DOMAIN_BEACON_PROPOSER, slot=0)
    d2 = compute_domain(DOMAIN_BEACON_PROPOSER, MAINNET_CHAIN_CONFIG.GENESIS_FORK_VERSION, gvr)
    assert d1 == d2
    assert d1[:4] == DOMAIN_BEACON_PROPOSER
    assert len(d1) == 32
    # different domain types differ only in prefix
    d3 = cfg.get_domain(DOMAIN_BEACON_ATTESTER, slot=0)
    assert d3[4:] == d1[4:] and d3[:4] != d1[:4]
    # domain for a post-fork epoch uses the new fork version
    d4 = cfg.get_domain(DOMAIN_BEACON_PROPOSER, slot=74240 * 32)
    assert d4 != d1


def test_fork_digest():
    gvr = b"\x01" * 32
    cfg = BeaconConfig(MAINNET_CHAIN_CONFIG, gvr)
    digest = cfg.fork_digest(ForkName.phase0)
    assert len(digest) == 4
    assert cfg.fork_name_from_digest(digest) == ForkName.phase0
    assert digest == compute_fork_digest(MAINNET_CHAIN_CONFIG.GENESIS_FORK_VERSION, gvr)


def test_far_future_forks_not_scheduled():
    cfg = ChainForkConfig(NETWORK_CONFIGS["minimal"])
    scheduled = [f.name for f in cfg.get_scheduled_forks()]
    assert scheduled == [ForkName.phase0]
    assert cfg.forks[ForkName.altair].epoch == FAR_FUTURE_EPOCH
