"""DB layer tests: controllers (memory + file log), repositories, BeaconDb
archive dual-index — mirroring the reference's db unit/e2e coverage."""

import os


from lodestar_tpu.db import BeaconDb, Bucket, FileDb, MemoryDb, Repository
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.types import get_types


def test_memory_db_ordered_streams():
    db = MemoryDb()
    db.put(b"\x01b", b"2")
    db.put(b"\x01a", b"1")
    db.put(b"\x02a", b"3")
    assert list(db.keys_stream(b"\x01", b"\x02")) == [b"\x01a", b"\x01b"]
    assert list(db.values_stream(b"\x01", b"\x02")) == [b"1", b"2"]
    db.delete(b"\x01a")
    assert db.get(b"\x01a") is None


def test_file_db_persistence(tmp_path):
    path = str(tmp_path / "chain.db")
    db = FileDb(path)
    db.put(b"k1", b"v1")
    db.batch_put([(b"k2", b"v2"), (b"k3", b"v3")])
    db.delete(b"k2")
    db.close()

    db2 = FileDb(path)
    assert db2.get(b"k1") == b"v1"
    assert db2.get(b"k2") is None
    assert db2.get(b"k3") == b"v3"
    db2.close()


def test_file_db_compaction(tmp_path):
    path = str(tmp_path / "c.db")
    db = FileDb(path)
    for i in range(300):
        db.put(b"key", str(i).encode())
    size_before = os.path.getsize(path)
    db.compact()
    assert os.path.getsize(path) < size_before
    db.close()
    db2 = FileDb(path)
    assert db2.get(b"key") == b"299"
    db2.close()


def test_repository_roundtrip():
    types = get_types(MINIMAL).phase0
    db = MemoryDb()
    repo = Repository(db, Bucket.allForks_block, types.SignedBeaconBlock.ssz_type)
    block = types.SignedBeaconBlock()
    block.message.slot = 42
    root = block.message.hash_tree_root()
    repo.put(root, block)
    got = repo.get(root)
    assert got is not None and got.message.slot == 42
    assert repo.has(root)
    assert list(repo.keys_stream()) == [root]
    repo.delete(root)
    assert not repo.has(root)


def test_beacon_db_archive_index():
    types = get_types(MINIMAL).phase0
    bdb = BeaconDb(types)
    b1 = types.SignedBeaconBlock()
    b1.message.slot = 10
    b2 = types.SignedBeaconBlock()
    b2.message.slot = 11
    bdb.archive_block(b1)
    bdb.archive_block(b2)
    got = bdb.get_archived_block_by_root(b2.message.hash_tree_root())
    assert got is not None and got.message.slot == 11
    # slot-ordered stream
    slots = [b.message.slot for b in bdb.block_archive.values_stream()]
    assert slots == [10, 11]


def test_repository_op_metrics_counted():
    """Per-op counters by bucket (reference db per-op metrics)."""
    from lodestar_tpu.db.controller import MemoryDb
    from lodestar_tpu.db.repository import Bucket, Repository
    from lodestar_tpu.ssz import uint64

    repo = Repository(MemoryDb(), Bucket.allForks_block, uint64)
    before = Repository.snapshot_op_metrics()
    repo.put(b"\x01" * 8, 7)
    repo.get(b"\x01" * 8)
    repo.get(b"\x02" * 8)
    after = Repository.snapshot_op_metrics()
    bucket = int(Bucket.allForks_block)
    assert after.get((bucket, "put"), 0) - before.get((bucket, "put"), 0) == 1
    assert after.get((bucket, "get"), 0) - before.get((bucket, "get"), 0) == 2
