"""Node composition + REST validator client e2e (reference analog:
`getDevBeaconNode`-based e2e + validator e2e with web3signer, SURVEY §4.4):
a BeaconNode with REST enabled, driven by a RestValidatorService over HTTP —
plus keystores, external signer, doppelganger, checkpoint sync, db resume."""

import pytest

from lodestar_tpu.api.client import BeaconApiClient
from lodestar_tpu.bls import api as bls
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.db import BeaconDb, MemoryDb
from lodestar_tpu.node import BeaconNode, NodeOptions, init_beacon_state
from lodestar_tpu.node.init_state import persist_state
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.types import get_types
from lodestar_tpu.validator import (
    DoppelgangerService,
    DoppelgangerStatus,
    ExternalSignerClient,
    ExternalSignerServer,
    RestValidatorService,
    SlashingProtection,
    ValidatorStore,
)

N = 16
SPE = MINIMAL.SLOTS_PER_EPOCH


@pytest.fixture(scope="module")
def node_env():
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    node = BeaconNode.init(
        config, types, state.copy(), NodeOptions(rest=True, rest_port=0)
    )
    yield config, types, node
    node.close()


def test_rest_validator_drives_chain(node_env):
    config, types, node = node_env
    client = BeaconApiClient(port=node.api_server.port)
    store = ValidatorStore(config, SlashingProtection(MemoryDb()))
    for i in range(N):
        store.add_secret_key(bls.interop_secret_key(i))
    service = RestValidatorService(config, types, client, store)

    for slot in range(1, SPE + 2):
        node.on_clock_slot(slot)
        service.on_slot(slot)
    head = node.chain.head_state
    assert head.state.slot >= SPE  # every proposal landed via REST
    # pool attestations made it into blocks
    head_block = node.chain.blocks[node.chain.head_root]
    assert len(head_block.message.body.attestations) > 0


def test_external_signer_roundtrip(node_env):
    config, types, node = node_env
    sks = [bls.interop_secret_key(50), bls.interop_secret_key(51)]
    server = ExternalSignerServer(sks)
    server.start()
    try:
        signer = ExternalSignerClient("127.0.0.1", server.port)
        assert signer.upcheck()
        keys = signer.list_pubkeys()
        assert keys == [sk.to_public_key().to_bytes() for sk in sks]
        store = ValidatorStore(config, SlashingProtection(MemoryDb()))
        pk = store.add_remote_key(keys[0], signer)
        sig = store.sign_randao(pk, 5)
        # remote signature must verify like a local one
        from lodestar_tpu.config.beacon_config import compute_signing_root
        from lodestar_tpu.params import DOMAIN_RANDAO
        from lodestar_tpu.ssz import uint64

        domain = config.get_domain(DOMAIN_RANDAO, 5)
        root = compute_signing_root(uint64.hash_tree_root(5 // SPE), domain)
        assert bls.verify(
            bls.PublicKey.from_bytes(keys[0]),
            root,
            bls.Signature.from_bytes(sig),
        )
    finally:
        server.close()


def test_keystore_roundtrip(tmp_path):
    pytest.importorskip("cryptography")  # EIP-2335 scrypt/AES

    from lodestar_tpu.validator.keystore import (
        KeystoreError,
        decrypt_keystore,
        encrypt_keystore,
        load_keystores_dir,
    )

    sk = bls.interop_secret_key(7)
    secret = sk.value.to_bytes(32, "big")
    ks = encrypt_keystore(secret, "correct horse battery staple")
    assert decrypt_keystore(ks, "correct horse battery staple") == secret
    with pytest.raises(KeystoreError):
        decrypt_keystore(ks, "wrong password")

    import json

    (tmp_path / "keystore-0.json").write_text(json.dumps(ks))
    loaded = load_keystores_dir(str(tmp_path), "correct horse battery staple")
    assert len(loaded) == 1
    assert loaded[0].to_public_key().to_bytes() == sk.to_public_key().to_bytes()


def test_doppelganger_state_machine():
    d = DoppelgangerService(epochs_to_check=2)
    d.register(1, current_epoch=10)
    d.register(2, current_epoch=10)
    assert not d.is_signing_safe(1)
    # epoch 11: validator 2 seen live → detected forever
    d.on_epoch(11, {2: True})
    assert d.status(2) == DoppelgangerStatus.DETECTED
    # epoch 12: validator 1 clean for 2 epochs → safe
    d.on_epoch(12, {})
    assert d.is_signing_safe(1)
    assert not d.is_signing_safe(2)
    assert d.any_detected()


def test_liveness_endpoint_and_doppelganger_gate(node_env):
    config, types, node = node_env
    client = BeaconApiClient(port=node.api_server.port)
    epoch = node.chain.head_state.current_epoch
    # indices that attested in test_rest_validator_drives_chain are live
    out = client.getLiveness(epoch, body=["0", "1"])
    assert isinstance(out, list) and len(out) == 2


def test_checkpoint_sync_and_db_resume(node_env):
    config, types, node = node_env
    client = BeaconApiClient(port=node.api_server.port)
    # checkpoint-sync path: download head state SSZ, anchor a new node
    data = client.getStateV2("head")
    ssz_bytes = bytes.fromhex(data["ssz"].removeprefix("0x"))
    db = BeaconDb(types, MemoryDb())
    state, origin = init_beacon_state(
        config,
        get_types(MINIMAL),
        db,
        checkpoint_state_bytes=ssz_bytes,
        # the test genesis is in the past; pin the clock inside the WS period
        current_epoch=node.chain.head_state.current_epoch,
    )
    assert origin == "checkpoint"
    assert state.slot == node.chain.head_state.state.slot

    # db-resume path: persist then re-init without a checkpoint
    persist_state(db, state)
    resumed, origin2 = init_beacon_state(config, get_types(MINIMAL), db)
    assert origin2 == "db"
    assert resumed.slot == state.slot

    # the checkpoint anchor actually boots a working node
    node2 = BeaconNode.init(config, types, state, NodeOptions(rest=False))
    assert node2.chain.head_state.state.slot == state.slot
    node2.close()


def test_rest_validator_registers_fee_recipient(node_env):
    """The validator client re-registers its fee recipient each duty
    refresh; block production then pays it (prepareBeaconProposerService)."""
    from lodestar_tpu.api.client import BeaconApiClient
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.db.controller import MemoryDb
    from lodestar_tpu.validator import (
        RestValidatorService,
        SlashingProtection,
        ValidatorStore,
    )

    config, types, node = node_env
    fee = bytes(range(20))
    client = BeaconApiClient("127.0.0.1", node.api_server.port)
    store = ValidatorStore(config, SlashingProtection(MemoryDb()))
    for i in range(4):
        store.add_secret_key(bls.interop_secret_key(i))
    service = RestValidatorService(config, types, client, store, fee_recipient=fee)
    service.update_duties(node.chain.head_state.epoch_ctx.current_epoch)
    for i in range(4):
        assert node.chain.beacon_proposer_cache.get(i) == fee
    assert node.chain.beacon_proposer_cache.get(7) == b"\x00" * 20
