"""Metrics tests: registry semantics, exposition format, HTTP endpoint."""

import urllib.request

from lodestar_tpu.metrics import MetricsRegistry, MetricsServer, create_beacon_metrics


def test_counter_gauge_histogram():
    r = MetricsRegistry()
    c = r.counter("requests_total", "reqs", label_names=("route",))
    c.inc(route="a")
    c.inc(2, route="a")
    c.inc(route="b")
    assert c.value(route="a") == 3
    g = r.gauge("head_slot", "slot")
    g.set(42)
    h = r.histogram("latency_seconds", "lat", buckets=(0.1, 1, 10))
    h.observe(0.05)
    h.observe(5)
    text = r.expose()
    assert 'requests_total{route="a"} 3' in text
    assert "head_slot 42" in text
    assert 'latency_seconds_bucket{le="0.1"} 1' in text
    assert 'latency_seconds_bucket{le="10.0"} 2' in text
    assert 'latency_seconds_bucket{le="+Inf"} 2' in text
    assert "latency_seconds_count 2" in text
    assert "# TYPE requests_total counter" in text


def test_histogram_timer():
    r = MetricsRegistry()
    h = r.histogram("op_seconds", "op")
    with h.time():
        pass
    assert h._totals[()] == 1


def test_beacon_metric_set_and_http_server():
    m = create_beacon_metrics()
    m.head_slot.set(7)
    m.bls_sets_total.inc(128)
    m.gossip_attestations_total.inc(outcome="ACCEPT")
    server = MetricsServer(m.registry, port=0)
    server.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics"
        ) as resp:
            body = resp.read().decode()
        assert "beacon_head_slot 7" in body
        assert "lodestar_bls_verifier_sets_total 128" in body
        assert 'beacon_gossip_attestation_total{outcome="ACCEPT"} 1' in body
    finally:
        server.close()


def test_network_metrics_exported_live():
    """Network heartbeat exports peers/mesh/queue gauges; gossip rx/tx
    counters move with real traffic (reference: gossipsub metric family)."""
    import asyncio

    from lodestar_tpu.metrics import create_beacon_metrics
    from lodestar_tpu.network.network import Network
    from lodestar_tpu.network.transport import NodeIdentity
    from tests.test_network_live import _fresh_chain, _produce_signed_block

    async def main():
        nets = []
        for i in range(2):
            config, types, chain = _fresh_chain()
            net = Network(
                config, types, chain,
                identity=NodeIdentity.from_seed(bytes([70 + i])),
                verify_signatures=False,
                metrics=create_beacon_metrics(),
            )
            await net.start()
            nets.append(net)
        a, b = nets
        try:
            await a.connect(*b.transport.listen_addr)
            for _ in range(3):
                await asyncio.sleep(0.05)
                for n in nets:
                    await n.gossip.heartbeat()
            signed = _produce_signed_block(a.config, a.types, a.chain, 1)
            b.chain.clock.set_slot(1)
            a.chain.process_block(signed, verify_signatures=False)
            await a.publish_block(signed)
            for _ in range(60):
                if b.metrics.gossip_rx_total.value(outcome="ACCEPT") >= 1:
                    break
                await asyncio.sleep(0.05)
            a._export_metrics()
            b._export_metrics()
            assert a.metrics.peers_connected.value() == 1
            assert a.metrics.gossip_tx_total.value() >= 1
            assert b.metrics.gossip_rx_total.value(outcome="ACCEPT") >= 1
            # prometheus text exposition includes the new families
            text = a.metrics.registry.expose()
            assert "lodestar_peers_connected 1" in text
            assert "lodestar_gossip_messages_sent_total" in text
        finally:
            for n in nets:
                await n.stop()

    asyncio.run(asyncio.wait_for(main(), 90.0))
