"""Metrics tests: registry semantics, exposition format, HTTP endpoint."""

import urllib.request

from lodestar_tpu.metrics import MetricsRegistry, MetricsServer, create_beacon_metrics


def test_counter_gauge_histogram():
    r = MetricsRegistry()
    c = r.counter("requests_total", "reqs", label_names=("route",))
    c.inc(route="a")
    c.inc(2, route="a")
    c.inc(route="b")
    assert c.value(route="a") == 3
    g = r.gauge("head_slot", "slot")
    g.set(42)
    h = r.histogram("latency_seconds", "lat", buckets=(0.1, 1, 10))
    h.observe(0.05)
    h.observe(5)
    text = r.expose()
    assert 'requests_total{route="a"} 3' in text
    assert "head_slot 42" in text
    assert 'latency_seconds_bucket{le="0.1"} 1' in text
    assert 'latency_seconds_bucket{le="10.0"} 2' in text
    assert 'latency_seconds_bucket{le="+Inf"} 2' in text
    assert "latency_seconds_count 2" in text
    assert "# TYPE requests_total counter" in text


def test_histogram_timer():
    r = MetricsRegistry()
    h = r.histogram("op_seconds", "op")
    with h.time():
        pass
    assert h._totals[()] == 1


def test_histogram_timer_with_labels():
    r = MetricsRegistry()
    h = r.histogram("stage_seconds", "s", label_names=("stage",))
    with h.time(stage="marshal"):
        pass
    with h.time(stage="marshal"):
        pass
    with h.time(stage="dispatch"):
        pass
    text = r.expose()
    assert 'stage_seconds_count{stage="marshal"} 2' in text
    assert 'stage_seconds_count{stage="dispatch"} 1' in text


def test_summary_exposition_format():
    r = MetricsRegistry()
    s = r.summary("batch_size", "sets per batch")
    s.observe(10)
    s.observe(30)
    assert s.sum() == 40 and s.count() == 2
    labeled = r.summary("wait_seconds", "w", label_names=("kind",))
    with labeled.time(kind="gossip"):
        pass
    text = r.expose()
    assert "# TYPE batch_size summary" in text
    assert "batch_size_sum 40" in text
    assert "batch_size_count 2" in text
    assert 'wait_seconds_count{kind="gossip"} 1' in text
    # summaries never emit bucket series
    assert "batch_size_bucket" not in text


def test_gauge_func_callback():
    r = MetricsRegistry()
    depth = [0]
    g = r.gauge_func("queue_depth", "live depth", fn=lambda: depth[0])
    assert g.value() == 0
    depth[0] = 7
    assert "queue_depth 7" in r.expose()  # read at collection time
    # late binding + broken-callback safety
    g.set_function(lambda: 1 / 0)
    assert g.value() == 0.0
    unbound = r.gauge_func("other_depth", "no fn yet")
    assert unbound.value() == 0.0
    unbound.set_function(lambda: 3)
    assert "other_depth 3" in r.expose()


def test_beacon_metric_set_and_http_server():
    m = create_beacon_metrics()
    m.head_slot.set(7)
    m.bls_sets_total.inc(128)
    m.gossip_attestations_total.inc(outcome="ACCEPT")
    server = MetricsServer(m.registry, port=0)
    server.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics"
        ) as resp:
            body = resp.read().decode()
        assert "beacon_head_slot 7" in body
        assert "lodestar_bls_verifier_sets_total 128" in body
        assert 'beacon_gossip_attestation_total{outcome="ACCEPT"} 1' in body
    finally:
        server.close()


def test_network_metrics_exported_live():
    """Network heartbeat exports peers/mesh/queue gauges; gossip rx/tx
    counters move with real traffic (reference: gossipsub metric family)."""
    import asyncio

    import pytest

    pytest.importorskip("cryptography")  # live transport identities

    from lodestar_tpu.metrics import create_beacon_metrics
    from lodestar_tpu.network.network import Network
    from lodestar_tpu.network.transport import NodeIdentity
    from tests.test_network_live import _fresh_chain, _produce_signed_block

    async def main():
        nets = []
        for i in range(2):
            config, types, chain = _fresh_chain()
            net = Network(
                config, types, chain,
                identity=NodeIdentity.from_seed(bytes([70 + i])),
                verify_signatures=False,
                metrics=create_beacon_metrics(),
            )
            await net.start()
            nets.append(net)
        a, b = nets
        try:
            await a.connect(*b.transport.listen_addr)
            for _ in range(3):
                await asyncio.sleep(0.05)
                for n in nets:
                    await n.gossip.heartbeat()
            signed = _produce_signed_block(a.config, a.types, a.chain, 1)
            b.chain.clock.set_slot(1)
            a.chain.process_block(signed, verify_signatures=False)
            await a.publish_block(signed)
            for _ in range(60):
                if b.metrics.gossip_rx_total.value(outcome="ACCEPT") >= 1:
                    break
                await asyncio.sleep(0.05)
            a._export_metrics()
            b._export_metrics()
            assert a.metrics.peers_connected.value() == 1
            assert a.metrics.gossip_tx_total.value() >= 1
            assert b.metrics.gossip_rx_total.value(outcome="ACCEPT") >= 1
            # prometheus text exposition includes the new families
            text = a.metrics.registry.expose()
            assert "lodestar_peers_connected 1" in text
            assert "lodestar_gossip_messages_sent_total" in text
        finally:
            for n in nets:
                await n.stop()

    asyncio.run(asyncio.wait_for(main(), 90.0))


def test_validator_monitor_tracks_duties():
    """Expanded ValidatorMonitor (reference validatorMonitor.ts): gossip
    sightings, inclusions with distance/correctness, proposals,
    aggregates, sync signatures, balances, epoch rollup + log lines."""
    from lodestar_tpu.metrics.registry import MetricsRegistry
    from lodestar_tpu.metrics.validator_monitor import ValidatorMonitor

    r = MetricsRegistry()
    m = ValidatorMonitor(r)
    for i in (1, 2, 3):
        m.register_validator(i)

    m.on_gossip_attestation(0, 1, delay_sec=0.5)
    m.on_attestation_included(0, [1, 2], 1, target_correct=True, head_correct=False)
    m.on_attestation_included(0, [1], 3, target_correct=False, head_correct=True)
    m.on_aggregate_published(0, 2)
    m.on_block_proposed(0, 3)
    m.on_sync_committee_message(0, 1)
    m.on_sync_signature_included(0, [1])
    m.on_balances(0, [0, 32_000_000_000, 31_500_000_000, 32_100_000_000])

    out = m.summarize_epoch(0)
    assert out[1].attestation_included and out[1].inclusion_distance == 1
    assert out[1].target_correct and out[1].head_correct  # OR across inclusions
    assert out[1].sync_signatures == 1 and out[1].sync_signatures_included == 1
    assert out[2].aggregates_published == 1
    assert out[3].blocks_proposed == 1 and not out[3].attestation_included
    assert out[1].balance_gwei == 32_000_000_000

    # epoch log lines render for operators
    class _Cap:
        lines = []

        def info(self, fmt, *args):
            _Cap.lines.append(fmt % args)

    m2 = ValidatorMonitor(r)
    m2.register_validator(9)
    m2.on_block_proposed(1, 9)
    m2.log_epoch(1, _Cap())
    assert any("v9" in l and "props=1" in l for l in _Cap.lines)


def test_full_node_registry_breadth_and_format():
    """Round-3 breadth pass (VERDICT r2 #8): a full node registry carries
    >=120 metric families and every family renders valid Prometheus text."""
    from lodestar_tpu.metrics.beacon import create_beacon_metrics
    from lodestar_tpu.metrics.gc_stats import GcMetrics
    from lodestar_tpu.metrics.validator_monitor import ValidatorMonitor

    m = create_beacon_metrics()
    ValidatorMonitor(m.registry)
    GcMetrics(m.registry)
    assert len(m.registry._metrics) >= 120

    # exercise the round-3 families through their public seams
    m.gossip_validation_total.inc(kind="beacon_attestation", outcome="accept")
    m.gossip_iwant_served_total.inc(3)
    m.reqresp_incoming_requests_total.inc(protocol="status")
    m.reqresp_bytes_sent_total.inc(512, protocol="beacon_blocks_by_range")
    m.sync_batches_in_state.set(2, state="downloading")
    m.eth1_follow_distance.set(2048)
    m.api_requests_total.inc(namespace="beacon", status="2xx")
    m.epoch_transition_seconds.observe(0.25)
    m.state_hash_seconds.observe(0.01)
    m.gossip_peers_by_score.set(5, band="positive")

    text = m.registry.expose()
    # every family has HELP+TYPE; labeled series render {k="v"} pairs
    assert text.count("# HELP") == len(m.registry._metrics)
    assert text.count("# TYPE") == len(m.registry._metrics)
    assert (
        'lodestar_gossip_validation_total{kind="beacon_attestation",'
        'outcome="accept"} 1' in text
        or 'lodestar_gossip_validation_total{outcome="accept",'
        'kind="beacon_attestation"} 1' in text
    )
    assert 'lodestar_eth1_follow_distance_blocks 2048' in text
    assert "lodestar_stfn_epoch_transition_seconds_bucket" in text
    # no duplicate family registrations
    names = [m2.name for m2 in m.registry._metrics]
    assert len(names) == len(set(names))


def test_dashboards_reference_real_metrics():
    """Every panel expression in dashboards/*.json must reference a
    metric family that actually exists in the live registry (VERDICT r4
    #8: dashboards backed by real metrics, enforced). Delegates token
    extraction to tools/check_dashboards (the single copy of the PromQL
    parsing rules) so this test cannot drift from the lint."""
    import glob
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(__file__), "..", "tools", "check_dashboards.py"
    )
    spec = importlib.util.spec_from_file_location("check_dashboards_tm", path)
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)

    known, _families = lint.registry_names()
    dash_dir = os.path.join(os.path.dirname(__file__), "..", "dashboards")
    files = sorted(glob.glob(os.path.join(dash_dir, "*.json")))
    assert len(files) >= 16  # reference parity (ISSUE 2)
    unknown = [
        (fname, title, name)
        for fname, title, name in lint.dashboard_refs(dash_dir)
        if name not in known
    ]
    assert not unknown, f"dashboard panels with unknown metrics: {unknown}"
