"""Metrics tests: registry semantics, exposition format, HTTP endpoint."""

import urllib.request

from lodestar_tpu.metrics import MetricsRegistry, MetricsServer, create_beacon_metrics


def test_counter_gauge_histogram():
    r = MetricsRegistry()
    c = r.counter("requests_total", "reqs", label_names=("route",))
    c.inc(route="a")
    c.inc(2, route="a")
    c.inc(route="b")
    assert c.value(route="a") == 3
    g = r.gauge("head_slot", "slot")
    g.set(42)
    h = r.histogram("latency_seconds", "lat", buckets=(0.1, 1, 10))
    h.observe(0.05)
    h.observe(5)
    text = r.expose()
    assert 'requests_total{route="a"} 3' in text
    assert "head_slot 42" in text
    assert 'latency_seconds_bucket{le="0.1"} 1' in text
    assert 'latency_seconds_bucket{le="10.0"} 2' in text
    assert 'latency_seconds_bucket{le="+Inf"} 2' in text
    assert "latency_seconds_count 2" in text
    assert "# TYPE requests_total counter" in text


def test_histogram_timer():
    r = MetricsRegistry()
    h = r.histogram("op_seconds", "op")
    with h.time():
        pass
    assert h._totals[()] == 1


def test_beacon_metric_set_and_http_server():
    m = create_beacon_metrics()
    m.head_slot.set(7)
    m.bls_sets_total.inc(128)
    m.gossip_attestations_total.inc(outcome="ACCEPT")
    server = MetricsServer(m.registry, port=0)
    server.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics"
        ) as resp:
            body = resp.read().decode()
        assert "beacon_head_slot 7" in body
        assert "lodestar_bls_verifier_sets_total 128" in body
        assert 'beacon_gossip_attestation_total{outcome="ACCEPT"} 1' in body
    finally:
        server.close()
