"""The docs/robustness.md chaos-drill runbook, automated (ISSUE 19).

The runbook was six curl-and-watch steps against a live dev node; each
drill here is the same scenario driven headless through the FULL gossip
-> import stack (GossipHandlers -> BeaconChain -> ThreadBufferedVerifier
-> SupervisedBlsVerifier -> device tier) with `testing/faults.py` armed
at the device seam, asserting the observable outcomes the runbook tells
the operator to watch: breaker transitions, fallback/retry/deadline
counters, mesh eviction, slot-milestone metrics, and a residue-free
teardown. Slow tier: the scheduled run (`pytest -m slow`) is the drill
cadence; the per-commit tier keeps the unit-level coverage in
tests/test_supervisor.py.
"""

import asyncio
import types

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from lodestar_tpu.bls import api as bls  # noqa: E402
from lodestar_tpu.chain.bls_verifier import (  # noqa: E402
    DeviceBlsVerifier,
    ThreadBufferedVerifier,
)
from lodestar_tpu.chain.supervisor import (  # noqa: E402
    BREAKER_CLOSED,
    BREAKER_OPEN,
    SupervisedBlsVerifier,
)
from lodestar_tpu.parallel.mesh import BlsMeshDispatcher  # noqa: E402
from lodestar_tpu.testing import faults  # noqa: E402

from test_supervisor import CountingCpu, _stub_kernels  # noqa: E402


@pytest.fixture(autouse=True)
def _no_fault_residue():
    """Runbook step 6 as an invariant: every drill must tear down to
    `active: false` — and no drill may inherit another's plan."""
    faults.clear(reset_counters=True)
    yield
    faults.clear(reset_counters=True)


def _drill_stack(device=None, **sup_kw):
    """The supervised gossip->import stack of docs/robustness.md, faults
    armable at the device seam. Returns (chain pieces, supervisor,
    metrics, push_block)."""
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.config.beacon_config import (
        BeaconConfig,
        ChainForkConfig,
    )
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.metrics import create_beacon_metrics
    from lodestar_tpu.network.gossip.encoding import encode_message
    from lodestar_tpu.network.gossip.handlers import GossipHandlers
    from lodestar_tpu.network.gossip.topic import GossipTopic, GossipType
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.state_transition import interop_genesis_state
    from lodestar_tpu.types import get_types

    types_mod = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(
        fork_config, types_mod, 16, genesis_time=1_600_000_000
    )
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    metrics = create_beacon_metrics()
    if device is None:
        device = DeviceBlsVerifier(observer=metrics.pipeline)
        _stub_kernels(device._inner)
    # the canary must marshal like production traffic (a real interop
    # pubkey; verdicts come from the stubbed kernels)
    canary = [bls.SignatureSet(
        pubkey=bls.PublicKey.from_bytes(bytes(state.validators[0].pubkey)),
        message=b"\x22" * 32,
        signature=b"\x11" * 96,
    )]
    sup_kw.setdefault("deadline_s", 5.0)
    sup_kw.setdefault("failure_threshold", 3)
    sup_kw.setdefault("retries", 1)
    sup_kw.setdefault("retry_base_delay_s", 0.001)
    sup_kw.setdefault("canary_thread", False)
    sup_kw.setdefault("canary_sets", canary)
    sup = SupervisedBlsVerifier(
        device, CountingCpu(True), observer=metrics.pipeline, **sup_kw
    )
    verifier = ThreadBufferedVerifier(sup, prom=metrics, max_wait_ms=10)
    chain = BeaconChain(config, types_mod, state, verifier=verifier)
    chain.metrics = metrics
    handlers = GossipHandlers(config, types_mod, chain)
    topic = GossipTopic(GossipType.beacon_block, b"\x01\x02\x03\x04")

    def push_block(slot):
        chain.clock.set_slot(slot)
        block = chain.produce_block(slot, randao_reveal=b"\x00" * 96)
        signed = types_mod.SignedBeaconBlock(
            message=block, signature=b"\x11" * 96
        )
        wire = encode_message(signed.serialize())
        return asyncio.run(handlers._process((topic, wire)))

    return chain, sup, metrics, push_block


def test_drill_storm_recovery_flaky_residue():
    """Runbook steps 1-3 + 5-6: baseline green, exception storm opens
    the breaker while every block still imports, the canary re-closes
    it, the flaky drill is rescued by the negative-verdict audit, and
    teardown leaves no residue."""
    from lodestar_tpu.network.gossip.gossipsub import ValidationResult

    chain, sup, metrics, push_block = _drill_stack()
    p = metrics.pipeline

    # 1. baseline: breaker closed, no faults, a block imports cleanly
    #    and the slot-milestone families record the import
    assert sup.breaker_state == BREAKER_CLOSED
    assert not faults.active()
    assert push_block(1) is ValidationResult.ACCEPT
    exposed = metrics.registry.expose()
    assert 'milestone="validated"' in exposed
    assert 'milestone="imported"' in exposed
    base_fallbacks = p.supervisor_fallbacks.value(reason="exception")

    # 2. exception storm: every device dispatch raises; imports continue
    #    on the oracle tier, the breaker opens after THRESHOLD failures
    faults.configure("exception")
    for slot in (2, 3, 4, 5):
        assert push_block(slot) is ValidationResult.ACCEPT
    assert sup.breaker_state == BREAKER_OPEN
    assert p.supervisor_breaker_state.value() == 2
    storm_fallbacks = (
        p.supervisor_fallbacks.value(reason="exception") - base_fallbacks
        + p.supervisor_fallbacks.value(reason="breaker_open")
    )
    assert storm_fallbacks >= 3, "every storm import was oracle-served"
    assert p.supervisor_both_tiers_failed.value() == 0
    assert sup.cpu.calls >= 4

    # 3. recovery: clear faults, one canary probe re-closes the breaker
    faults.clear()
    assert sup.probe() is True
    assert sup.breaker_state == BREAKER_CLOSED
    assert p.supervisor_canary.value(outcome="ok") >= 1
    assert p.supervisor_transitions.value(to="closed") >= 1
    assert push_block(6) is ValidationResult.ACCEPT

    # 5. flaky drill: corrupted device verdicts (True->False) are
    #    overturned by the CPU oracle audit — gossip verdicts stay
    #    correct while the mismatch counter ticks
    mismatches = p.supervisor_verdict_mismatches.value()
    faults.configure("flaky")
    assert push_block(7) is ValidationResult.ACCEPT
    assert p.supervisor_verdict_mismatches.value() > mismatches
    assert faults.snapshot()["injected"]["flaky"] >= 1

    # 6. residue check: teardown disarms and zeroes the injection counts
    faults.clear(reset_counters=True)
    snap = faults.snapshot()
    assert snap == {"active": False, "modes": {}, "injected": {}}
    assert p.waiter_timeouts.value() == 0


def test_drill_wedge_deadline_blowout():
    """Runbook step 4: a wedged dispatch (sleep past the supervisor
    deadline) is abandoned, the import is served by the oracle tier,
    the deadline counter ticks — and the waiter escape hatch stays at
    ZERO (the supervisor catches the wedge first)."""
    from lodestar_tpu.network.gossip.gossipsub import ValidationResult

    chain, sup, metrics, push_block = _drill_stack(
        deadline_s=0.4, failure_threshold=10
    )
    p = metrics.pipeline

    faults.configure("deadline:1.5")
    assert push_block(1) is ValidationResult.ACCEPT
    assert p.supervisor_deadline_exceeded.value() >= 1
    assert faults.snapshot()["injected"]["deadline"] >= 1
    assert p.supervisor_fallbacks.value(reason="deadline") >= 1
    assert p.supervisor_both_tiers_failed.value() == 0
    # the whole point of the layered policy: no gossip thread ever hit
    # the last-resort waiter timeout
    assert p.waiter_timeouts.value() == 0
    # the breaker did not open for a single wedge under a high threshold
    assert sup.breaker_state == BREAKER_CLOSED

    faults.clear()
    assert push_block(2) is ValidationResult.ACCEPT


class _MeshedStubDevice:
    """Device tier serving from a real BlsMeshDispatcher (stub per-chip
    verifiers): the chip fault fires inside `dispatch_*` exactly like a
    sick chip on hardware, and the supervisor's eviction policy runs the
    real mesh state machine."""

    def __init__(self):
        def factory(kind, devices, axis):
            stub = types.SimpleNamespace()
            stub.submit = lambda g, a, b: True
            return stub

        self.mesh = BlsMeshDispatcher(
            ["c0", "c1", "c2", "c3"], verifier_factory=factory
        )
        self._g = types.SimpleNamespace(pk_x=np.ones((4, 2, 3), np.float32))
        self.dispatches = 0

    def _dispatch(self):
        self.dispatches += 1
        out = self.mesh.dispatch_grouped(self._g, None, None)
        return bool(out)

    def verify_signature_sets(self, sets):
        return self._dispatch()

    def verify_signature_sets_individual(self, sets):
        ok = self._dispatch()
        return [ok] * len(sets)

    # supervisor mesh seam
    def mesh_evict(self, chip=None, reason="failure"):
        return self.mesh.evict(chip=chip, reason=reason)

    def mesh_readmit(self):
        return self.mesh.readmit()

    def mesh_has_evicted(self):
        return self.mesh.has_evicted()


def test_drill_chip_fault_evicts_and_serving_continues():
    """The mid-run eviction drill: a one-shot chip fault on a mesh
    dispatch evicts the attributed chip, the SAME import retries on the
    surviving mesh and succeeds — device tier, no CPU fallback, breaker
    closed, eviction visible in the lodestar_bls_mesh_* families."""
    from lodestar_tpu.network.gossip.gossipsub import ValidationResult

    dev = _MeshedStubDevice()
    chain, sup, metrics, push_block = _drill_stack(device=dev)
    # rebind the mesh observer onto the stack's pipeline so eviction
    # metrics land in the registry the assertions read
    dev.mesh.observer = metrics.pipeline
    p = metrics.pipeline

    faults.configure("chip:1")
    assert push_block(1) is ValidationResult.ACCEPT
    # chip 1 evicted, serving shrank 4 -> 2 chips, same-call retry won
    assert dev.mesh.has_evicted()
    assert dev.mesh.size == 2
    assert 1 not in dev.mesh._serving_chips()
    assert p.mesh_evictions.value(reason="InjectedChipFault") >= 1
    assert faults.snapshot()["injected"]["chip"] == 1
    # eviction is NOT a device failure: no fallback, breaker closed
    assert sup.cpu.calls == 0
    assert sup.breaker_state == BREAKER_CLOSED
    assert p.supervisor_both_tiers_failed.value() == 0

    # one-shot: the next import serves on the survivors with no new fault
    assert push_block(2) is ValidationResult.ACCEPT
    assert faults.snapshot()["injected"]["chip"] == 1
