"""Validator client tests: slashing protection safety conditions,
interchange round-trip, and a validator-service-driven chain reaching
justification (reference analog: validator unit tests + sim)."""

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain import BeaconChain
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.db import MemoryDb
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.types import get_types
from lodestar_tpu.validator import (
    SlashingError,
    SlashingProtection,
    ValidatorService,
    ValidatorStore,
)

SPE = MINIMAL.SLOTS_PER_EPOCH
PK = b"\xaa" * 48


@pytest.fixture()
def protection():
    return SlashingProtection(MemoryDb())


class TestSlashingProtection:
    def test_block_double_proposal_rejected(self, protection):
        protection.check_and_insert_block_proposal(PK, 10, b"\x01" * 32)
        protection.check_and_insert_block_proposal(PK, 11, b"\x02" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_block_proposal(PK, 11, b"\x03" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_block_proposal(PK, 5, b"\x04" * 32)
        # identical re-sign is allowed
        protection.check_and_insert_block_proposal(PK, 11, b"\x02" * 32)

    def test_attestation_double_vote_rejected(self, protection):
        protection.check_and_insert_attestation(PK, 1, 2, b"\x01" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 1, 2, b"\x02" * 32)
        protection.check_and_insert_attestation(PK, 1, 2, b"\x01" * 32)  # same root ok

    def test_surrounding_vote_rejected(self, protection):
        protection.check_and_insert_attestation(PK, 3, 4, b"\x01" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 2, 5, b"\x02" * 32)

    def test_surrounded_vote_rejected(self, protection):
        protection.check_and_insert_attestation(PK, 2, 6, b"\x01" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 3, 5, b"\x02" * 32)

    def test_normal_progression_allowed(self, protection):
        for e in range(1, 10):
            protection.check_and_insert_attestation(PK, e, e + 1, bytes([e]) * 32)

    def test_min_max_surround_across_pruned_history(self, protection):
        """VERDICT r3 Missing #4: surround detection must survive the
        512-target exact-root prune — the min/max distance spans answer
        for votes whose targets are long gone (reference:
        minMaxSurround.ts)."""
        # vote (10, 11), then 600 adjacent votes pushing it out of the
        # exact-root window
        protection.check_and_insert_attestation(PK, 10, 11, b"\x01" * 32)
        for e in range(12, 612):
            protection.check_and_insert_attestation(PK, e - 1, e, bytes([e % 256]) * 32)
        rec = protection.atts.get(PK)
        assert str(11) not in rec["targets"], "test needs (10,11) pruned"
        # surrounding the pruned vote: s=9 < 10, t=700 > 11
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 9, 700, b"\x02" * 32)

    def test_surrounded_across_pruned_history(self, protection):
        # wide vote (100, 640), then many adjacent votes to prune it
        protection.check_and_insert_attestation(PK, 100, 640, b"\x01" * 32)
        for e in range(641, 1400):
            protection.check_and_insert_attestation(PK, e - 1, e, bytes([e % 256]) * 32)
        rec = protection.atts.get(PK)
        assert str(640) not in rec["targets"]
        # surrounded by the pruned wide vote: 100 < 200, 300 < 640
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 200, 300, b"\x02" * 32)

    def test_double_vote_below_retained_window_refused(self, protection):
        for e in range(1, 600):
            protection.check_and_insert_attestation(PK, e - 1, e, bytes([e % 256]) * 32)
        rec = protection.atts.get(PK)
        pruned_below = rec["pruned_below"]
        assert pruned_below > 0
        # a target inside the pruned region cannot be double-vote-checked
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(
                PK, 0, pruned_below, b"\xfe" * 32
            )

    def test_source_below_span_floor_refused(self):
        p = SlashingProtection(MemoryDb(), max_epoch_lookback=64)
        p.check_and_insert_attestation(PK, 500, 501, b"\x01" * 32)
        # floor advanced to 501 - 64 = 437; unknown deep history refused
        with pytest.raises(SlashingError):
            p.check_and_insert_attestation(PK, 100, 502, b"\x02" * 32)

    def test_wide_vote_beyond_lookback_detected(self):
        """A vote wider than the span lookback cannot ride the bounded
        walks — it must land on the wide list and still bite."""
        p = SlashingProtection(MemoryDb(), max_epoch_lookback=64)
        p.check_and_insert_attestation(PK, 100, 1000, b"\x01" * 32)  # wide
        # surrounded by the wide vote, source far beyond the walk bound
        with pytest.raises(SlashingError):
            p.check_and_insert_attestation(PK, 500, 600, b"\x02" * 32)
        # surrounding the wide vote
        with pytest.raises(SlashingError):
            p.check_and_insert_attestation(PK, 99, 1001, b"\x03" * 32)

    def test_old_format_record_migrates_to_spans(self, protection):
        """Pre-span records (targets only) must regain surround protection
        via the one-time replay migration, not silently lose it."""
        # simulate an old-format record: targets dict without span keys
        protection.atts.put(
            PK,
            {
                "targets": {
                    "60": {"source": 50, "root": "aa" * 32},
                    "61": {"source": 60, "root": "bb" * 32},
                },
                "max_target": 61,
                "min_source": 50,
            },
        )
        # surrounding vote of the old (50, 60) must still be refused
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 40, 100, b"\x02" * 32)
        # double vote at a migrated target keeps its root
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 50, 60, b"\x0c" * 32)
        protection.check_and_insert_attestation(PK, 50, 60, b"\xaa" * 32)
        # votes below the migration floor are refused, not guessed at
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 30, 45, b"\x03" * 32)
        # normal progression continues
        protection.check_and_insert_attestation(PK, 61, 62, b"\x04" * 32)

    def test_migration_replay_failure_raises_pruned_below(self):
        """If the one-time replay migration cannot re-insert a retained
        vote (a wide vote advanced the span floor past a later vote's
        source), the lost vote's target must be fenced off via
        pruned_below — otherwise a second vote at that target with a
        different root would pass the double-vote check (slashable)."""
        p = SlashingProtection(MemoryDb(), max_epoch_lookback=64)
        p.atts.put(
            PK,
            {
                "targets": {
                    # wide vote: replaying it advances the floor to 136
                    "200": {"source": 0, "root": "aa" * 32},
                    # source 100 < 136 → fails replay, would be lost
                    "210": {"source": 100, "root": "bb" * 32},
                },
                "max_target": 210,
                "min_source": 0,
            },
        )
        # benign new vote triggers the migration
        p.check_and_insert_attestation(PK, 211, 212, b"\x01" * 32)
        # signing again at the lost target with a DIFFERENT root must be
        # refused — history there is unknown, not absent
        with pytest.raises(SlashingError):
            p.check_and_insert_attestation(PK, 150, 210, b"\x02" * 32)

    def test_span_property_random(self, protection):
        """Property test: the span answers must equal the brute-force
        surround scan over the FULL vote history (never pruned here)."""
        import random

        rng = random.Random(1234)
        accepted: list[tuple[int, int]] = []
        used_targets: dict[int, int] = {}
        for i in range(400):
            s = rng.randrange(0, 256)
            t = s + rng.randrange(1, 40)
            brute_reject = any(
                (s < s2 and t > t2) or (s > s2 and t < t2)
                for s2, t2 in accepted
            )
            if t in used_targets:
                brute_reject = brute_reject or used_targets[t] != i % 7
            root = bytes([i % 7]) * 32
            try:
                protection.check_and_insert_attestation(PK, s, t, root)
                ok = True
            except SlashingError:
                ok = False
            if t in used_targets:
                # double-vote path: accepted iff same root
                assert ok == (used_targets[t] == i % 7), (i, s, t)
            else:
                assert ok == (not brute_reject), (i, s, t, accepted)
            if ok and t not in used_targets:
                accepted.append((s, t))
                used_targets[t] = i % 7

    def test_interchange_roundtrip(self, protection):
        protection.check_and_insert_block_proposal(PK, 7, b"\x0b" * 32)
        protection.check_and_insert_attestation(PK, 1, 2, b"\x0a" * 32)
        exported = protection.export_interchange(b"\x00" * 32, [PK])
        assert exported["metadata"]["interchange_format_version"] == "5"

        fresh = SlashingProtection(MemoryDb())
        fresh.import_interchange(exported)
        with pytest.raises(SlashingError):
            fresh.check_and_insert_block_proposal(PK, 7, b"\xff" * 32)
        with pytest.raises(SlashingError):
            fresh.check_and_insert_attestation(PK, 1, 2, b"\xff" * 32)


def test_validator_service_drives_chain_to_justification():
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    n = 16
    state = interop_genesis_state(fork_config, types, n, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    chain = BeaconChain(config, types, state)
    store = ValidatorStore(config, SlashingProtection(MemoryDb()))
    for i in range(n):
        store.add_secret_key(bls.interop_secret_key(i))
    service = ValidatorService(config, types, chain, store)

    # duty discovery covers everyone exactly once per epoch
    duties = service.get_attester_duties(0)
    assert sorted(d.validator_index for d in duties) == list(range(n))
    proposer_duties = service.get_proposer_duties(0)
    assert len(proposer_duties) == SPE  # we own all validators

    for slot in range(1, 3 * SPE + 1):
        chain.clock.set_slot(slot)
        signed = service.propose_block_if_due(slot)
        assert signed is not None  # all validators are ours
        service.attest_if_due(slot)

    assert chain.justified_checkpoint[0] >= 1
    # slashing protection must now refuse re-signing an old block slot
    pk0 = store.pubkeys[0]
    blk = types.BeaconBlock(slot=1, proposer_index=0)
    seen_slots = {
        d.slot for d in service.get_proposer_duties(chain.head_state.current_epoch)
    }
    with pytest.raises(SlashingError):
        # any of our keys that proposed earlier refuses slot 1 again
        proposer_pk = next(
            pk for pk in store.pubkeys
            if (store.protection.blocks.get(pk) or {}).get("max_slot", -1) >= 1
        )
        store.sign_block(proposer_pk, types, blk)
