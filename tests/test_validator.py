"""Validator client tests: slashing protection safety conditions,
interchange round-trip, and a validator-service-driven chain reaching
justification (reference analog: validator unit tests + sim)."""

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain import BeaconChain
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.db import MemoryDb
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state
from lodestar_tpu.types import get_types
from lodestar_tpu.validator import (
    SlashingError,
    SlashingProtection,
    ValidatorService,
    ValidatorStore,
)

SPE = MINIMAL.SLOTS_PER_EPOCH
PK = b"\xaa" * 48


@pytest.fixture()
def protection():
    return SlashingProtection(MemoryDb())


class TestSlashingProtection:
    def test_block_double_proposal_rejected(self, protection):
        protection.check_and_insert_block_proposal(PK, 10, b"\x01" * 32)
        protection.check_and_insert_block_proposal(PK, 11, b"\x02" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_block_proposal(PK, 11, b"\x03" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_block_proposal(PK, 5, b"\x04" * 32)
        # identical re-sign is allowed
        protection.check_and_insert_block_proposal(PK, 11, b"\x02" * 32)

    def test_attestation_double_vote_rejected(self, protection):
        protection.check_and_insert_attestation(PK, 1, 2, b"\x01" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 1, 2, b"\x02" * 32)
        protection.check_and_insert_attestation(PK, 1, 2, b"\x01" * 32)  # same root ok

    def test_surrounding_vote_rejected(self, protection):
        protection.check_and_insert_attestation(PK, 3, 4, b"\x01" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 2, 5, b"\x02" * 32)

    def test_surrounded_vote_rejected(self, protection):
        protection.check_and_insert_attestation(PK, 2, 6, b"\x01" * 32)
        with pytest.raises(SlashingError):
            protection.check_and_insert_attestation(PK, 3, 5, b"\x02" * 32)

    def test_normal_progression_allowed(self, protection):
        for e in range(1, 10):
            protection.check_and_insert_attestation(PK, e, e + 1, bytes([e]) * 32)

    def test_interchange_roundtrip(self, protection):
        protection.check_and_insert_block_proposal(PK, 7, b"\x0b" * 32)
        protection.check_and_insert_attestation(PK, 1, 2, b"\x0a" * 32)
        exported = protection.export_interchange(b"\x00" * 32, [PK])
        assert exported["metadata"]["interchange_format_version"] == "5"

        fresh = SlashingProtection(MemoryDb())
        fresh.import_interchange(exported)
        with pytest.raises(SlashingError):
            fresh.check_and_insert_block_proposal(PK, 7, b"\xff" * 32)
        with pytest.raises(SlashingError):
            fresh.check_and_insert_attestation(PK, 1, 2, b"\xff" * 32)


def test_validator_service_drives_chain_to_justification():
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    n = 16
    state = interop_genesis_state(fork_config, types, n, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    chain = BeaconChain(config, types, state)
    store = ValidatorStore(config, SlashingProtection(MemoryDb()))
    for i in range(n):
        store.add_secret_key(bls.interop_secret_key(i))
    service = ValidatorService(config, types, chain, store)

    # duty discovery covers everyone exactly once per epoch
    duties = service.get_attester_duties(0)
    assert sorted(d.validator_index for d in duties) == list(range(n))
    proposer_duties = service.get_proposer_duties(0)
    assert len(proposer_duties) == SPE  # we own all validators

    for slot in range(1, 3 * SPE + 1):
        chain.clock.set_slot(slot)
        signed = service.propose_block_if_due(slot)
        assert signed is not None  # all validators are ours
        service.attest_if_due(slot)

    assert chain.justified_checkpoint[0] >= 1
    # slashing protection must now refuse re-signing an old block slot
    pk0 = store.pubkeys[0]
    blk = types.BeaconBlock(slot=1, proposer_index=0)
    seen_slots = {
        d.slot for d in service.get_proposer_duties(chain.head_state.current_epoch)
    }
    with pytest.raises(SlashingError):
        # any of our keys that proposed earlier refuses slot 1 again
        proposer_pk = next(
            pk for pk in store.pubkeys
            if (store.protection.blocks.get(pk) or {}).get("max_slot", -1) >= 1
        )
        store.sign_block(proposer_pk, types, blk)
