"""Planted metric-discipline violations (fixture — never imported)."""


class FixtureMetrics:
    def __init__(self, registry):
        self.batches = registry.counter(
            "lodestar_fixture_batches", "batches", ("outcome",)
        )
        # 1: same family redeclared with a different label set
        self.batches_dup = registry.counter(
            "lodestar_fixture_batches", "batches", ("result", "tier")
        )
        self.depth = registry.gauge("lodestar_fixture_depth", "queue depth")
        self.latency = registry.summary(
            "lodestar_fixture_latency", "seconds", ("stage",)
        )
        # 3: declared, never touched again, not on any dashboard
        self.orphan = registry.counter("lodestar_fixture_orphan", "unused")

    def record(self, ok):
        self.batches.inc(outcome="ok" if ok else "fail")
        self.depth.set(3.0)
        # 2: label name disagrees with the declaration ("stage")
        self.latency.observe(0.5, phase="verify")


def scrape_filter():
    # 4: full-string literal that matches no declared family
    return ["lodestar_fixture_nonexistent_total"]
