"""Planted exception-hygiene violations (fixture — never imported)."""


def bare_except():
    try:
        return 1 / 0
    except:  # 1: bare except
        return None


def silent_broad():
    try:
        return 1 / 0
    except Exception:  # 2: silently swallowed
        pass


def silent_broad_continue():
    for i in range(3):
        try:
            _ = 1 / i
        except Exception:  # 3: silently swallowed via continue
            continue
    return None


def handled_broad(log):
    try:
        return 1 / 0
    except Exception as e:  # acting on the error: fine
        log.warning("division failed: %s", e)
        return None


def narrow_silent():
    try:
        return 1 / 0
    except ZeroDivisionError:  # narrow + silent: fine (explicit contract)
        pass
