"""Planted lock-discipline violations (fixture — never imported)."""

import threading
import time


class Buffered:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries = []  # guarded-by: _lock
        self._done = threading.Event()

    def add_locked(self, item):
        with self._lock:
            self._entries.append(item)  # attribute method call: fine
            self._entries = list(self._entries)  # rebind under lock: fine

    def add_unlocked(self, item):
        self._entries = [item]  # 1: guarded write without the lock

    def add_conditionally(self, item):
        if item:
            self._entries = [item]  # 2: guarded write in a branch, no lock

    def sleep_while_locked(self):
        with self._lock:
            time.sleep(0.5)  # 3: blocking call while holding the lock

    def wait_while_locked(self):
        with self._lock:
            self._done.wait()  # 4: untimed wait while holding the lock

    def wait_bounded(self):
        with self._lock:
            return self._done.wait(timeout=1.0)  # bounded: fine

    def join_while_locked(self, worker):
        with self._lock:
            worker.join()  # 5: unbounded join while holding the lock
