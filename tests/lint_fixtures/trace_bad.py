"""Planted trace-safety violations (fixture — never imported; linted as
text by tests/test_lint.py). Each numbered site must produce a finding."""

import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def kernel_item(x):
    return x.item()  # 1: host sync under trace


@functools.partial(jax.jit, static_argnames=("flag",))
def kernel_static(x, flag=True):
    if flag:
        return x + 1
    return x


def call_sites(x):
    # 2: unhashable list in a static position
    return kernel_static(x, flag=[1, 2])


@jax.jit
def kernel_asarray(x):
    return np.asarray(x)  # 3: device->host pull


@jax.jit
def kernel_branch(x):
    if jnp.any(x > 0):  # 4: Python branch on a traced value
        return x
    return -x


@jax.jit
def kernel_float(x):
    return float(x) * 2.0  # 5: ConcretizationTypeError at trace time


@jax.jit
def kernel_sync(x):
    y = (x * 2).block_until_ready()  # 6: device sync under trace
    return y


def _helper(x):
    return x.tolist()  # 7: transitive — called from a kernel below


@jax.jit
def kernel_transitive(x):
    return _helper(x)


def body(x):
    return jax.device_get(x)  # 8: kernel-ness via jit() call reference


wrapped = jax.jit(body)
