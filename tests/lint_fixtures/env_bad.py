"""Planted env-registry violations (fixture — never imported)."""

import os


def raw_getenv():
    return os.getenv("LODESTAR_TPU_SOME_KNOB")  # 1: raw read


def raw_environ_get():
    return os.environ.get("LODESTAR_TPU_OTHER_KNOB", "1")  # 2: raw read


def raw_subscript():
    return os.environ["LODESTAR_TPU_THIRD_KNOB"]  # 3: raw subscript read


def unregistered_typed_read():
    from lodestar_tpu.utils.env import env_bool

    return env_bool("LODESTAR_TPU_NOT_A_REAL_KNOB")  # 4: not in registry


def allowed_write():
    os.environ["LODESTAR_TPU_SOME_KNOB"] = "1"  # writes are legal
    return None


def allowed_other_prefix():
    return os.getenv("XLA_FLAGS")  # non-LODESTAR knobs are out of scope
