"""Planted trace-safety violations inside Pallas kernel bodies (fixture —
never imported; linted as text by tests/test_lint.py). Pallas kernels are
the nastiest place for host syncs: under ``interpret=True`` they "work",
then explode when Mosaic lowers them on the TPU path. Each numbered site
must produce a finding."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def bad_branch_kernel(x_ref, o_ref):
    v = x_ref[...]
    if jnp.any(v > 0):  # 1: Python branch on a traced value
        v = -v
    o_ref[...] = jnp.asarray(np.asarray(v))  # 2: device->host pull


def _helper(v):
    return v.item()  # 3: transitive — called from the kernel below


def bad_transitive_kernel(x_ref, o_ref):
    o_ref[...] = _helper(x_ref[...])


def launch(x):
    y = pl.pallas_call(
        bad_branch_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
    return pl.pallas_call(
        bad_transitive_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(y)
