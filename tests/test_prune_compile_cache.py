"""Unit tests for the compile-cache LRU pruner (tools/prune_compile_cache).

Pure-filesystem policy tests: a fake cache directory with sized + aged
files, no jax involvement. The pruner's contract is what warmup.py leans
on every pass — oldest-first, bound respected, dry-run inert, missing
dir a no-op — so these run in the default tier."""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "tools")
)

import prune_compile_cache as pcc  # noqa: E402

MB = 1 << 20


def _make(cache, name, size_mb, age):
    """Write a `size_mb` file whose atime/mtime are `age` ticks old."""
    path = os.path.join(cache, name)
    with open(path, "wb") as f:
        f.write(b"\0" * (size_mb * MB))
    base = 1_700_000_000  # arbitrary fixed epoch keeps ordering explicit
    os.utime(path, (base - age, base - age))
    return path


@pytest.fixture()
def cache(tmp_path):
    d = tmp_path / "jax_cache"
    d.mkdir()
    return str(d)


def test_scan_sorts_oldest_first(cache):
    _make(cache, "newish", 1, age=10)
    _make(cache, "oldest", 1, age=99)
    _make(cache, "newest", 1, age=1)
    names = [os.path.basename(p) for _, _, p in pcc.scan(cache)]
    assert names == ["oldest", "newish", "newest"]


def test_scan_missing_dir_is_empty(tmp_path):
    assert pcc.scan(str(tmp_path / "nope")) == []


def test_scan_recency_is_max_of_atime_mtime(cache):
    # an old-mtime entry with a RECENT atime (cache hit on a noatime-free
    # mount) must sort as recent, not as a prune candidate
    hit = _make(cache, "hit", 1, age=99)
    _make(cache, "cold", 1, age=50)
    st = os.stat(hit)
    os.utime(hit, (st.st_mtime + 98, st.st_mtime))  # touched atime only
    names = [os.path.basename(p) for _, _, p in pcc.scan(cache)]
    assert names == ["cold", "hit"]


def test_prune_respects_bound_lru_order(cache):
    _make(cache, "a_oldest", 4, age=40)
    _make(cache, "b_middle", 4, age=30)
    _make(cache, "c_recent", 4, age=20)
    _make(cache, "d_newest", 4, age=10)
    # 16 MB total, bound 10 MB: drop the two oldest (16->8 <= 10)
    r = pcc.prune(cache, limit_gb=10 * MB / (1 << 30))
    assert [os.path.basename(p) for p in r["removed"]] == [
        "a_oldest", "b_middle",
    ]
    assert r["removed_bytes"] == 8 * MB
    assert r["total_bytes"] == 8 * MB <= r["limit_bytes"]
    assert sorted(os.listdir(cache)) == ["c_recent", "d_newest"]


def test_prune_under_bound_is_noop(cache):
    _make(cache, "only", 1, age=5)
    r = pcc.prune(cache, limit_gb=1.0)
    assert r["removed"] == [] and r["removed_bytes"] == 0
    assert os.listdir(cache) == ["only"]


def test_prune_dry_run_deletes_nothing(cache):
    _make(cache, "a", 4, age=40)
    _make(cache, "b", 4, age=10)
    r = pcc.prune(cache, limit_gb=4 * MB / (1 << 30), dry_run=True)
    assert [os.path.basename(p) for p in r["removed"]] == ["a"]
    assert sorted(os.listdir(cache)) == ["a", "b"]


def test_prune_missing_dir_is_noop(tmp_path):
    r = pcc.prune(str(tmp_path / "nope"), limit_gb=0.0, aot_dir=None)
    assert r == {
        "entries": 0, "entries_remaining": 0, "total_bytes": 0,
        "limit_bytes": 0, "removed": [], "removed_bytes": 0,
        "dirs": [str(tmp_path / "nope")], "aot_removed": 0,
    }


def test_prune_skips_subdirectories(cache):
    # jax may namespace entries in subdirs; the pruner only bounds the
    # flat entry files and must not crash on (or delete) directories
    os.mkdir(os.path.join(cache, "subdir"))
    _make(cache, "entry", 4, age=10)
    r = pcc.prune(cache, limit_gb=1 * MB / (1 << 30))
    assert [os.path.basename(p) for p in r["removed"]] == ["entry"]
    assert os.listdir(cache) == ["subdir"]


def test_default_limit_env_override(monkeypatch):
    monkeypatch.delenv(pcc.ENV_LIMIT, raising=False)
    assert pcc.default_limit_gb() == pcc.DEFAULT_LIMIT_GB
    monkeypatch.setenv(pcc.ENV_LIMIT, "6.5")
    assert pcc.default_limit_gb() == 6.5
    monkeypatch.setenv(pcc.ENV_LIMIT, "banana")
    assert pcc.default_limit_gb() == pcc.DEFAULT_LIMIT_GB


def test_cli_dry_run(cache, capsys):
    _make(cache, "a", 2, age=20)
    _make(cache, "b", 2, age=10)
    rc = pcc.main([
        "--cache-dir", cache,
        "--limit-gb", str(2 * MB / (1 << 30)),
        "--dry-run",
    ])
    assert rc == 0
    assert "would prune 1 entries" in capsys.readouterr().out
    assert sorted(os.listdir(cache)) == ["a", "b"]
