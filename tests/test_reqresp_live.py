"""Req/Resp over the live secure transport: two real nodes, real TCP.

Reference analog: `beacon-node/test/e2e/network/reqresp.test.ts` — two
in-process nodes with real libp2p streams exchanging Status / blocks.
"""

import asyncio
import threading

import pytest

# live req/resp (noise transport identities) needs the `cryptography`
# wheel, which minimal CI images may lack — skip, not error
pytest.importorskip("cryptography")

from lodestar_tpu.network.reqresp.handlers import ReqRespHandlers
from lodestar_tpu.network.reqresp.service import RemotePeer, ReqRespService, RequestError
from lodestar_tpu.network.transport import NodeIdentity, Transport

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow



def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 60.0))


def _make_chain_with_blocks(n_blocks=4):
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.params import DOMAIN_RANDAO
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.state_transition import interop_genesis_state, process_slots
    from lodestar_tpu.state_transition.block import _epoch_signing_root
    from lodestar_tpu.types import get_types
    from tests.test_chain import _sign_block, _sk

    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, 16, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    chain = BeaconChain(config, types, state)
    blocks = []
    for slot in range(1, n_blocks + 1):
        chain.clock.set_slot(slot)
        trial = chain.head_state.copy()
        if slot > trial.state.slot:
            process_slots(trial, types, slot)
        proposer = trial.epoch_ctx.get_beacon_proposer(slot)
        reveal = _sk(proposer).sign(
            _epoch_signing_root(0, config.get_domain(DOMAIN_RANDAO, slot))
        ).to_bytes()
        block = chain.produce_block(slot, randao_reveal=reveal)
        signed = _sign_block(config, types, block)
        chain.process_block(signed, verify_signatures=False)
        blocks.append(signed)
    return config, types, chain, blocks


@pytest.fixture(scope="module")
def chain_env():
    return _make_chain_with_blocks()


async def _two_nodes(chain_env):
    config, types, chain, blocks = chain_env
    t_server = Transport(NodeIdentity.from_seed(b"server"))
    t_client = Transport(NodeIdentity.from_seed(b"client"))
    server_svc = ReqRespService(
        t_server, ReqRespHandlers(config, types, chain), types
    )
    client_svc = ReqRespService(
        t_client, ReqRespHandlers(config, types, chain), types
    )
    host, port = await t_server.listen()
    await t_client.dial(host, port)
    return t_server, t_client, server_svc, client_svc


def test_status_exchange_over_wire(chain_env):
    async def main():
        t_server, t_client, _, client_svc = await _two_nodes(chain_env)
        status = await client_svc.status(t_server.peer_id)
        assert status.head_slot == chain_env[2].head_state.state.slot
        assert bytes(status.head_root) == chain_env[2].head_root
        await t_client.close()
        await t_server.close()

    run(main())


def test_blocks_by_range_and_root_over_wire(chain_env):
    async def main():
        _, types, chain, blocks = chain_env
        t_server, t_client, _, client_svc = await _two_nodes(chain_env)
        got = await client_svc.beacon_blocks_by_range(t_server.peer_id, 1, 10)
        assert [b.message.slot for b in got] == [1, 2, 3, 4]
        root = blocks[1].message.hash_tree_root()
        got2 = await client_svc.beacon_blocks_by_root(t_server.peer_id, [root])
        assert len(got2) == 1 and got2[0].message.hash_tree_root() == root
        await t_client.close()
        await t_server.close()

    run(main())


def test_ping_metadata_goodbye(chain_env):
    async def main():
        t_server, t_client, _, client_svc = await _two_nodes(chain_env)
        seq = await client_svc.ping(t_server.peer_id, 7)
        assert seq == 0
        md = await client_svc.metadata(t_server.peer_id)
        assert md.seq_number == 0
        await client_svc.goodbye(t_server.peer_id, reason=1)
        await t_client.close()
        await t_server.close()

    run(main())


def test_request_rate_limit_rejects_spam(chain_env):
    async def main():
        t_server, t_client, server_svc, client_svc = await _two_nodes(chain_env)
        server_svc.request_rate.limit = 3
        ok, rejected = 0, 0
        for _ in range(6):
            try:
                await client_svc.ping(t_server.peer_id)
                ok += 1
            except RequestError as e:
                assert e.code in ("RESOURCE_UNAVAILABLE", "EMPTY_RESPONSE")
                rejected += 1
        assert ok == 3 and rejected == 3
        await t_client.close()
        await t_server.close()

    run(main())


def test_remote_peer_sync_adapter(chain_env):
    """RemotePeer drives the async client from a sync worker thread —
    the IPeer surface range-sync consumes."""
    _, types, chain, blocks = chain_env
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    try:
        async def setup():
            return await _two_nodes(chain_env)

        t_server, t_client, _, client_svc = asyncio.run_coroutine_threadsafe(
            setup(), loop
        ).result(30)
        peer = RemotePeer(client_svc, t_server.peer_id, loop)
        status = peer.status()
        assert status.head_slot == 4
        got = peer.beacon_blocks_by_range(1, 2)
        assert [b.message.slot for b in got] == [1, 2]
        asyncio.run_coroutine_threadsafe(t_client.close(), loop).result(10)
        asyncio.run_coroutine_threadsafe(t_server.close(), loop).result(10)
    finally:
        loop.call_soon_threadsafe(loop.stop)
        thread.join(timeout=5)
