"""Known-answer + spec-defined-behavior conformance tests.

Two tiers of external validation (VERDICT r2 Missing #3 — official
conformance evidence without network egress):

1. KNOWN-ANSWER constants with published provenance: the eth2 interop
   validator pubkeys (eth2.0-pm interop spec; embedded verbatim in every
   client's fixtures), the BLS12-381 generator coordinates and field/group
   moduli (IETF pairing-friendly-curves draft / zkcrypto spec), the
   zero-subtree hash chain (sha256 of 64 zero bytes onward), and the ZCash
   compressed-infinity encodings. These bytes were NOT produced by this
   repo — if our serialization, keygen, or hashing drifted, these fail.

2. SPEC-DEFINED BEHAVIOR cases mirroring the official `bls12-381-tests`
   suite's semantics (reference pins it v0.1.1 —
   beacon-node/test/spec/specTestVersioning.ts:17-33): infinity
   pubkey/signature rejection, non-subgroup rejection, malformed
   encodings, aggregate edge cases, and the eth2 G2-infinity
   special cases. Each case's expected outcome is fixed by the IETF BLS
   draft + consensus spec, not by our implementation.
"""

import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.bls.curve import (
    PointG1,
    PointG2,
    g1_from_bytes,
    g1_to_bytes,
    g2_from_bytes,
)
from lodestar_tpu.bls.fields import P, R
from lodestar_tpu.ssz.hashing import ZERO_HASHES, hash_pair

# --- tier 1: published constants --------------------------------------------

# eth2 interop validator pubkeys (secret keys sk_i = int(sha256(uint256(i)))
# mod r — eth2.0-pm/interop/mocked_start): the first two appear verbatim in
# client test fixtures across implementations.
INTEROP_PUBKEYS = {
    0: "a99a76ed7796f7be22d5b7e85deeb7c5677e88e511e0b337618f8c4eb61349b4"
       "bf2d153f649f7b53359fe8b94a38e44c",
    1: "b89bebc699769726a318c8e9971bd3171297c61aea4a6578a7a4f94b547dcba5"
       "bac16a89108b6b6a1fe3695d1a874a0b",
}

# BLS12-381 G1 generator (IETF pairing-friendly-curves §4.2.1 / zkcrypto).
G1_GEN_X = int(
    "17f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
    "6c55e83ff97a1aeffb3af00adb22c6bb", 16
)
G1_GEN_Y = int(
    "08b3f481e3aaa0f1a09e30ed741d8ae4fcf5e095d5d00af600db18cb2c04b3ed"
    "d03cc744a2888ae40caa232946c5e7e1", 16
)
# field modulus / subgroup order (published)
P_PUBLISHED = int(
    "1a0111ea397fe69a4b1ba7b6434bacd764774b84f38512bf6730d2a0f6b0f624"
    "1eabfffeb153ffffb9feffffffffaaab", 16
)
R_PUBLISHED = int(
    "73eda753299d7d483339d80809a1d80553bda402fffe5bfeffffffff00000001", 16
)


def test_interop_pubkeys_match_published():
    for idx, hexpk in INTEROP_PUBKEYS.items():
        pk = bls.interop_secret_key(idx).to_public_key()
        assert pk.to_bytes().hex() == hexpk


def test_curve_constants_match_published():
    assert P == P_PUBLISHED
    assert R == R_PUBLISHED
    gen = PointG1.generator().to_affine()
    assert gen[0].n == G1_GEN_X
    assert gen[1].n == G1_GEN_Y
    # generator has order exactly r
    assert (PointG1.generator() * R).is_infinity()
    assert (PointG2.generator() * R).is_infinity()


def test_zero_subtree_hashes_match_published():
    # sha256 of 64 zero bytes — the universally-known zero-pair hash
    assert ZERO_HASHES[1].hex() == (
        "f5a5fd42d16a20302798ef6ed309979b43003d2320d9f0e8ea9831a92759fb4b"
    )
    # next level, also widely embedded in deposit-contract fixtures
    assert ZERO_HASHES[2].hex() == (
        "db56114e00fdd4c1f85c892bf35ac9a89289aaecb1ebd0a96cde606a748b5d71"
    )
    assert hash_pair(ZERO_HASHES[1], ZERO_HASHES[1]) == ZERO_HASHES[2]


def test_compressed_infinity_encodings():
    # ZCash serialization: infinity = 0xc0 then zeros (both groups)
    inf_g1 = bytes([0xC0]) + b"\x00" * 47
    inf_g2 = bytes([0xC0]) + b"\x00" * 95
    assert g1_from_bytes(inf_g1).is_infinity()
    assert g2_from_bytes(inf_g2).is_infinity()
    assert g1_to_bytes(PointG1.zero()) == inf_g1


def test_dst_is_the_consensus_pop_suite():
    assert bls.DST_G2 == b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


# --- tier 2: bls12-381-tests-shaped behavior cases ---------------------------


def _sk(i):
    return bls.interop_secret_key(i)


MSG = b"\xab" * 32


def test_sign_verify_roundtrip():
    sk = _sk(0)
    sig = sk.sign(MSG)
    assert bls.verify(sk.to_public_key(), MSG, sig)


def test_verify_wrong_message_false():
    sk = _sk(0)
    assert not bls.verify(sk.to_public_key(), b"\xcd" * 32, sk.sign(MSG))


def test_verify_wrong_key_false():
    assert not bls.verify(_sk(1).to_public_key(), MSG, _sk(0).sign(MSG))


def test_infinity_pubkey_rejected_by_keyvalidate():
    # official case: deserializing the infinity pubkey must fail KeyValidate
    with pytest.raises(bls.BlsError):
        bls.PublicKey.from_bytes(bytes([0xC0]) + b"\x00" * 47)


def test_infinity_signature_never_verifies():
    sk = _sk(0)
    inf_sig = bls.Signature.from_bytes(bytes([0xC0]) + b"\x00" * 95)
    assert not bls.verify(sk.to_public_key(), MSG, inf_sig)


def test_non_subgroup_g2_rejected():
    # find an x whose curve point is NOT in the order-r subgroup: E'(Fq2)
    # has cofactor h2 >> 1, so a random curve point almost surely fails
    from lodestar_tpu.bls.fields import Fq2
    from lodestar_tpu.bls.curve import B2, g2_to_bytes

    x = Fq2.from_ints(5, 1)
    while True:
        y2 = x * x * x + B2
        y = y2.sqrt()
        if y is not None:
            pt = PointG2(x, y, Fq2.one())
            if not pt.is_in_subgroup():
                break
        x = x + Fq2.from_ints(1, 0)
    raw = g2_to_bytes(pt)
    with pytest.raises(bls.BlsError):
        bls.Signature.from_bytes(raw)


def test_malformed_lengths_rejected():
    with pytest.raises((bls.BlsError, ValueError)):
        bls.PublicKey.from_bytes(b"\x01" * 47)
    with pytest.raises((bls.BlsError, ValueError)):
        bls.Signature.from_bytes(b"\x01" * 95)
    # x >= p must be rejected
    bad = bytearray((P_PUBLISHED).to_bytes(48, "big"))
    bad[0] |= 0x80
    with pytest.raises((bls.BlsError, ValueError)):
        bls.PublicKey.from_bytes(bytes(bad))


def test_aggregate_empty_errors():
    # official aggregate case: [] is invalid
    with pytest.raises(bls.BlsError):
        bls.aggregate_signatures([])
    with pytest.raises(bls.BlsError):
        bls.aggregate_pubkeys([])


def test_aggregate_verify_distinct_messages():
    sks = [_sk(i) for i in range(3)]
    msgs = [bytes([i]) * 32 for i in range(3)]
    agg = bls.aggregate_signatures(
        [sk.sign(m) for sk, m in zip(sks, msgs)]
    )
    assert bls.aggregate_verify(
        [sk.to_public_key() for sk in sks], msgs, agg
    )
    # tampering one message fails
    msgs[1] = b"\x99" * 32
    assert not bls.aggregate_verify(
        [sk.to_public_key() for sk in sks], msgs, agg
    )


def test_fast_aggregate_verify_shared_message():
    sks = [_sk(i) for i in range(4)]
    agg = bls.aggregate_signatures([sk.sign(MSG) for sk in sks])
    pks = [sk.to_public_key() for sk in sks]
    assert bls.fast_aggregate_verify(pks, MSG, agg)
    assert not bls.fast_aggregate_verify(pks[:3], MSG, agg)


def test_fast_aggregate_verify_empty_pubkeys_false():
    # official fast_aggregate_verify case: na_pubkeys → False (the eth2
    # eth_fast_aggregate_verify G2_POINT_AT_INFINITY exception is a
    # DIFFERENT function defined in the consensus specs)
    sig = _sk(0).sign(MSG)
    assert not bls.fast_aggregate_verify([], MSG, sig)


def test_aggregate_matches_manual_point_sum():
    sks = [_sk(i) for i in range(5)]
    agg = bls.aggregate_pubkeys([sk.to_public_key() for sk in sks])
    acc = PointG1.zero()
    for sk in sks:
        acc = acc + sk.to_public_key().point
    assert agg.point == acc
    # signature side too
    sigs = [sk.sign(MSG) for sk in sks]
    agg_sig = bls.aggregate_signatures(sigs)
    acc2 = PointG2.zero()
    for s in sigs:
        acc2 = acc2 + s.point
    assert agg_sig.point == acc2


def test_signature_set_batch_consistency():
    # verify_signature_sets must agree with per-set verify (official
    # batch-verify semantics: all-or-nothing over the same predicate)
    sets = []
    for i in range(3):
        sk = _sk(i)
        m = bytes([i ^ 0x5A]) * 32
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(), message=m, signature=sk.sign(m).to_bytes()
            )
        )
    assert bls.verify_signature_sets(sets)
    bad = list(sets)
    bad[2] = bls.SignatureSet(
        pubkey=bad[2].pubkey, message=bad[2].message,
        signature=_sk(9).sign(bad[2].message).to_bytes(),
    )
    assert not bls.verify_signature_sets(bad)


# --- RFC 9380 hash-to-curve conformance (VERDICT r3 #6) ---------------------
#
# Suite BLS12381G2_XMD:SHA-256_SSWU_RO_, DST QUUX-V01-CS02-… — the RFC's
# own test-vector suite (Appendix J.10.1). Provenance: ALL FIVE vectors
# below (msg = "", "abc", "abcdef0123456789", q128_…, a512_…) are the
# published RFC 9380 J.10.1 values, verified verbatim against the RFC
# text (independently re-checked character-for-character in round-4
# review). They are external conformance anchors, not outputs of this
# repo's pipeline.

RFC9380_G2_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"

RFC9380_G2_RO_VECTORS = {
    # msg: (x_c0, x_c1, y_c0, y_c1) — RFC 9380 J.10.1 anchor (verified)
    b"": (
        0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A,
        0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D,
        0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92,
        0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6,
    ),
    b"abc": (
        0x02C2D18E033B960562AAE3CAB37A27CE00D80CCD5BA4B7FE0E7A210245129DBEC7780CCC7954725F4168AFF2787776E6,
        0x139CDDBCCDC5E91B9623EFD38C49F81A6F83F175E80B06FC374DE9EB4B41DFE4CA3A230ED250FBE3A2ACF73A41177FD8,
        0x1787327B68159716A37440985269CF584BCB1E621D3A7202BE6EA05C4CFE244AEB197642555A0645FB87BF7466B2BA48,
        0x00AA65DAE3C8D732D10ECD2C50F8A1BAF3001578F71C694E03866E9F3D49AC1E1CE70DD94A733534F106D4CEC0EDDD16,
    ),
    b"abcdef0123456789": (
        0x121982811D2491FDE9BA7ED31EF9CA474F0E1501297F68C298E9F4C0028ADD35AEA8BB83D53C08CFC007C1E005723CD0,
        0x190D119345B94FBD15497BCBA94ECF7DB2CBFD1E1FE7DA034D26CBBA169FB3968288B3FAFB265F9EBD380512A71C3F2C,
        0x05571A0F8D3C08D094576981F4A3B8EDA0A8E771FCDCC8ECCEAF1356A6ACF17574518ACB506E435B639353C2E14827C8,
        0x0BB5E7572275C567462D91807DE765611490205A941A5A6AF3B1691BFE596C31225D3AABDF15FAFF860CB4EF17C7C3BE,
    ),
    b"q128_" + b"q" * 123: (
        0x066733149A8744073CCBBC2561A1F2A382A00194C5444CFE248F5777B4E380E7B0D78570CF45624BC60D8993B9AED231,
        0x070FB99A28B6427A4EF6D754A0BBEC85F5DA79B61EF85DE1923BCE24FCD56B5EE500FF0DB6C4484764BBF66F73D1C789,
        0x0B6726C135E5FCAEBF7902FC648B921A90184802C6365BD24D1B685B995D4312F41C68F9B75C7FC18D6F341A3DF5C7DA,
        0x106B75C6496E3408374454F55566A28DD6D5D6D4E98B13EA1BA974152B33EAF27A3D2B27BCE9C7E1DADB684B9C402357,
    ),
    b"a512_" + b"a" * 512: (
        0x01A6BA2F9A11FA5598B2D8ACE0FBE0A0EACB65DECEB476FBBCB64FD24557C2F4B18ECFC5663E54AE16A84F5AB7F62534,
        0x11FCA2FF525572795A801EED17EB12785887C7B63FB77A42BE46CE4A34131D71F7A73E95FEE3F812AEA3DE78B4D01569,
        0x0B6798718C8AED24BC19CB27F866F1C9EFFCDBF92397AD6448B5C9DB90D2B9DA6CBABF48ADC1ADF59A1A28344E79D57E,
        0x03A47F8E6D1763BA0CAD63D6114C0ACCBEF65707825A511B251A660A9B3994249AE4E63FAC38B23DA0C398689EE2AB52,
    ),
}


def test_rfc9380_g2_vectors_python_oracle():
    from lodestar_tpu.bls.hash_to_curve import hash_to_g2

    for msg, (xc0, xc1, yc0, yc1) in RFC9380_G2_RO_VECTORS.items():
        p = hash_to_g2(msg, dst=RFC9380_G2_DST)
        ax, ay = p.to_affine()
        assert (ax.c0.n, ax.c1.n) == (xc0, xc1), msg[:16]
        assert (ay.c0.n, ay.c1.n) == (yc0, yc1), msg[:16]


def test_rfc9380_g2_vectors_native_c_tier():
    from lodestar_tpu import native

    if not native.HAVE_NATIVE_BLS:
        import pytest

        pytest.skip("native BLS tier unavailable")
    from lodestar_tpu.ops.limbs import fp_from_mont_host

    for msg, (xc0, xc1, yc0, yc1) in RFC9380_G2_RO_VECTORS.items():
        rc, limbs = native.bls_hash_to_g2(msg, RFC9380_G2_DST)
        assert rc == 0
        got = tuple(
            fp_from_mont_host(limbs[i][j]) for i in (0, 1) for j in (0, 1)
        )
        assert got == (xc0, xc1, yc0, yc1), msg[:16]


# --- deterministic sign KATs (VERDICT r4 #6) --------------------------------
#
# Fixed (sk, msg) → exact 96-byte signature, asserted byte-identical on the
# Python oracle and native C tiers (and accepted by the device verifier —
# slow tier, see test_sign_kats_device_tier). Provenance: egress is zero,
# so these bytes cannot be copied from `bls12-381-tests`; instead every
# pinned signature is re-derived INSIDE the test by an independent affine
# double-and-add ladder written on plain ints (sharing only the published
# modulus and curve equation with the library) applied to the RFC-9380-
# anchored H(msg) — a wrong-but-self-consistent scalar-mul in the library
# fails the in-test cross-check, and a drifted serialization fails the
# pinned bytes. The secret keys are the eth2 interop keys whose G1
# pubkeys are already externally anchored above.

SIGN_KATS = [
    # (interop sk index, msg, signature hex)
    (0, b"\xab" * 32,
     "945d41c805215d034c33b31030b689490efc6783263250e5fdd03df37e0e0ab2"
     "6e2c1ad97ea71f741f2d7bdb59d4bc9e1220dd2822d582c1a2e7f5590753ae84"
     "faf5f8d13857f4d98ba5f9783f8e146562a40561209fde0015006b4786895be1"),
    (1, b"\x00" * 32,
     "b47a50461cbc0fb57fea230031591b1eac23f921e346fafc346db4bc23d1d982"
     "617d81ddbe45b9c90a9be3a98e6a8daa1600e4e6ef3bea34a8944d01a0f67cee"
     "b63088df9ef9350d7a3d318a19afca4c8cbb2a41aabe074b79a2dc3e8132398c"),
    (2, bytes(range(32)),
     "b7b3aeb39b9a21c3454ed5eff7302e3e010adda3f9859d60f7cf1664129b9791"
     "c69a7ac16405a1c2fb737d0d0f2d1bcc145f1a3707e880890fc2840591a8f5f9"
     "c00a9159353fac358ecb98e73a3c60551a868f294f0e7f5ec647eabecd9213c6"),
]


def _indep_g2_scalar_mul(k: int, pt):
    """[k]·pt by affine double-and-add on plain ints — deliberately NOT
    the library's point code (independent cross-check of scalar mul)."""

    def f2mul(a, b):
        (a0, a1), (b0, b1) = a, b
        return ((a0 * b0 - a1 * b1) % P, (a0 * b1 + a1 * b0) % P)

    def f2sub(a, b):
        return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)

    def f2inv(a):
        a0, a1 = a
        d = pow(a0 * a0 + a1 * a1, -1, P)
        return (a0 * d % P, -a1 * d % P)

    def pt_add(p, q):
        if p is None:
            return q
        if q is None:
            return p
        if p[0] == q[0]:
            if p[1] != q[1]:
                return None
            num = f2mul((3, 0), f2mul(p[0], p[0]))
            den = f2inv(f2mul((2, 0), p[1]))
        else:
            num = f2sub(q[1], p[1])
            den = f2inv(f2sub(q[0], p[0]))
        lam = f2mul(num, den)
        x = f2sub(f2sub(f2mul(lam, lam), p[0]), q[0])
        y = f2sub(f2mul(lam, f2sub(p[0], x)), p[1])
        return (x, y)

    acc = None
    while k:
        if k & 1:
            acc = pt_add(acc, pt)
        pt = pt_add(pt, pt)
        k >>= 1
    return acc


@pytest.mark.parametrize("idx,msg,sig_hex", SIGN_KATS)
def test_sign_kats_python_oracle(idx, msg, sig_hex):
    from lodestar_tpu.bls.hash_to_curve import hash_to_g2

    sk = bls.interop_secret_key(idx)
    sig = sk.sign(msg)
    assert sig.to_bytes().hex() == sig_hex
    # independent re-derivation: [sk]·H(msg) by the in-test affine ladder
    hx, hy = hash_to_g2(msg).to_affine()
    exp = _indep_g2_scalar_mul(
        int.from_bytes(sk.to_bytes(), "big"),
        ((hx.c0.n, hx.c1.n), (hy.c0.n, hy.c1.n)),
    )
    gx, gy = sig.point.to_affine()
    assert ((gx.c0.n, gx.c1.n), (gy.c0.n, gy.c1.n)) == exp
    # and it verifies
    assert bls.verify(sk.to_public_key(), msg, sig)


@pytest.mark.parametrize("idx,msg,sig_hex", SIGN_KATS)
def test_sign_kats_native_c_tier(idx, msg, sig_hex):
    from lodestar_tpu import native

    if not native.HAVE_NATIVE_BLS:
        pytest.skip("native BLS tier unavailable")
    sk = bls.interop_secret_key(idx)
    rc, out = native.bls_sign(sk.to_bytes(), msg, bls.DST_G2)
    assert rc == 0
    assert out == bytes.fromhex(sig_hex)


@pytest.mark.slow
def test_sign_kats_device_tier():
    """The device batch verifier must accept the pinned signatures and
    reject a tampered one (KATs through the TPU kernel path)."""
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    sets = []
    for idx, msg, sig_hex in SIGN_KATS:
        sk = bls.interop_secret_key(idx)
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=bytes.fromhex(sig_hex),
            )
        )
    v = TpuBlsVerifier(buckets=(4,))
    assert v.verify_signature_sets(sets)
    bad = list(sets)
    bad[1] = bls.SignatureSet(
        pubkey=bad[1].pubkey, message=bad[1].message,
        signature=bytes.fromhex(SIGN_KATS[2][2]),
    )
    assert not v.verify_signature_sets(bad)


def test_sign_rejects_out_of_range_secret_keys():
    # bls12-381-tests sign edge semantics: sk = 0 and sk >= r are invalid
    from lodestar_tpu.bls.fields import R as _R

    for v in (0, _R, _R + 5):
        with pytest.raises(bls.BlsError):
            bls.SecretKey.from_bytes(v.to_bytes(32, "big"))


def test_rfc9380_dst_independence():
    """Same message under the consensus POP DST must NOT equal the QUUX
    vectors (domain separation is the whole point of the DST)."""
    from lodestar_tpu.bls.hash_to_curve import DST_G2, hash_to_g2

    ax, _ = hash_to_g2(b"", dst=DST_G2).to_affine()
    anchor = RFC9380_G2_RO_VECTORS[b""]
    assert (ax.c0.n, ax.c1.n) != (anchor[0], anchor[1])
