"""Unit tests for the small services: attnets subscriptions, peer scoring
and pruning, reprocess queue, validator monitor."""

from lodestar_tpu.chain.reprocess import ReprocessController
from lodestar_tpu.metrics import MetricsRegistry
from lodestar_tpu.metrics.validator_monitor import ValidatorMonitor
from lodestar_tpu.network.peers import (
    PeerAction,
    PeerManager,
    PeerRpcScoreStore,
    ScoreState,
)
from lodestar_tpu.network.subnets import AttnetsService
from lodestar_tpu.params import ATTESTATION_SUBNET_COUNT


def test_attnets_rotation_and_enr():
    svc = AttnetsService(node_id=b"\x01" * 32, slots_per_epoch=8)
    svc.rotate(epoch=10, validator_count=2)
    subs = svc.active_subnets(10)
    assert subs and all(0 <= s < ATTESTATION_SUBNET_COUNT for s in subs)
    # deterministic within a period
    again = AttnetsService(node_id=b"\x01" * 32, slots_per_epoch=8)
    again.rotate(epoch=10, validator_count=2)
    assert again.active_subnets(10) == subs
    # short-lived duty subscription not in ENR
    svc.subscribe_committee(subnet=7, until_epoch=12)
    assert 7 in svc.active_subnets(11)
    assert not svc.enr_attnets(11)[7] or 7 in {s.subnet for s in svc.long_lived}
    # expiry
    assert 7 not in svc.active_subnets(12)


def test_peer_scores_decay_and_ban():
    now = [1000.0]
    store = PeerRpcScoreStore(time_fn=lambda: now[0])
    store.apply_action("p1", PeerAction.MidToleranceError)
    assert store.state("p1") == ScoreState.Healthy
    for _ in range(10):
        store.apply_action("p1", PeerAction.LowToleranceError)
    assert store.state("p1") == ScoreState.Banned
    # decay recovers over time
    now[0] += 3600
    assert store.state("p1") != ScoreState.Banned
    store.apply_action("p2", PeerAction.Fatal)
    assert store.state("p2") == ScoreState.Banned


def test_peer_manager_heartbeat_prunes():
    now = [0.0]
    pm = PeerManager(target_peers=2, time_fn=lambda: now[0])
    for i in range(4):
        assert pm.on_connect(f"p{i}")
    pm.report_peer("p0", PeerAction.Fatal)     # banned
    pm.report_peer("p1", PeerAction.LowToleranceError)  # worst healthy
    dropped = pm.heartbeat()
    assert "p0" in dropped
    assert len(pm.peers) <= 2
    # banned peers cannot reconnect
    assert not pm.on_connect("p0")


def test_reprocess_queue():
    now = [0.0]
    rc = ReprocessController(time_fn=lambda: now[0])
    root = b"\x0a" * 32
    assert rc.wait_for_block(root, "att1")
    assert rc.wait_for_block(root, "att2")
    assert rc.on_block_imported(root) == ["att1", "att2"]
    assert rc.on_block_imported(root) == []
    # expiry path
    rc.wait_for_block(b"\x0b" * 32, "stale")
    now[0] += 10
    assert rc.prune() == 1


def test_validator_monitor():
    reg = MetricsRegistry()
    vm = ValidatorMonitor(reg)
    vm.register_validator(3)
    vm.register_validator(4)
    vm.on_attestation_included(
        epoch=1, indices=[3, 9], inclusion_distance=1,
        target_correct=True, head_correct=False,
    )
    vm.on_block_proposed(epoch=1, proposer_index=4)
    summary = vm.summarize_epoch(1)
    assert summary[3].attestation_included and summary[3].target_correct
    assert not summary[4].attestation_included
    assert summary[4].blocks_proposed == 1
    text = reg.expose()
    assert 'validator_monitor_attestation_included_total{index="3"} 1' in text
    assert 'validator_monitor_attestation_missed_total{index="4"} 1' in text


def test_weak_subjectivity_period():
    from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.state_transition import CachedBeaconState, interop_genesis_state
    from lodestar_tpu.state_transition.weak_subjectivity import (
        compute_weak_subjectivity_period,
        is_within_weak_subjectivity_period,
    )
    from lodestar_tpu.types import get_types

    types = get_types(MINIMAL).phase0
    fc = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fc, types, 16, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    cached = CachedBeaconState(config, state, MINIMAL)
    ws = compute_weak_subjectivity_period(cached)
    # with full 32-ETH balances the ws period is at least the withdrawability delay
    assert ws >= config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    assert is_within_weak_subjectivity_period(cached, ws_checkpoint_epoch=0)


def test_syncnets_service_membership_subscriptions():
    """Reference syncnetsService.ts: duty-driven subscriptions per sync
    period, pruned on expiry, advertised via the syncnets bitfield."""
    from lodestar_tpu.network.subnets import SyncnetsService

    svc = SyncnetsService(slots_per_epoch=8)
    svc.subscribe_committee_member(1, until_epoch=10)
    svc.subscribe_committee_member(3, until_epoch=5)
    assert svc.active_subnets(epoch=4) == {1, 3}
    assert svc.enr_syncnets(epoch=4) == [False, True, False, True]
    assert svc.active_subnets(epoch=7) == {1}
    svc.prune(epoch=7)
    assert len(svc.subscriptions) == 1
    assert svc.active_subnets(epoch=11) == set()
