"""Sync e2e: a fresh node range-syncs from a producing node through the
real req/resp wire codec; unknown-block sync resolves parent chains
(reference analog: sync e2e + multi-node sim, SURVEY.md §4.4-4.5)."""

import pytest

from lodestar_tpu.chain import BeaconChain
from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
from lodestar_tpu.network.reqresp.handlers import ReqRespHandlers
from lodestar_tpu.params import DOMAIN_RANDAO
from lodestar_tpu.params.presets import MINIMAL
from lodestar_tpu.state_transition import interop_genesis_state, process_slots
from lodestar_tpu.state_transition.block import _epoch_signing_root
from lodestar_tpu.sync import LocalPeer, RangeSync, UnknownBlockSync
from lodestar_tpu.sync.range_sync import RangeSyncError
from lodestar_tpu.types import get_types
from tests.test_chain import _attest_head, _sign_block, _sk

SPE = MINIMAL.SLOTS_PER_EPOCH
N = 16


@pytest.fixture(scope="module")
def two_nodes():
    """Node A produces 2 epochs of blocks; node B starts at genesis."""
    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    node_a = BeaconChain(config, types, state.copy())
    for slot in range(1, 2 * SPE + 1):
        node_a.clock.set_slot(slot)
        trial = node_a.head_state.copy()
        if slot > trial.state.slot:
            process_slots(trial, types, slot)
        proposer = trial.epoch_ctx.get_beacon_proposer(slot)
        reveal = _sk(proposer).sign(
            _epoch_signing_root(slot // SPE, config.get_domain(DOMAIN_RANDAO, slot))
        ).to_bytes()
        block = node_a.produce_block(slot, randao_reveal=reveal)
        node_a.process_block(
            _sign_block(config, types, block), verify_signatures=False
        )
        _attest_head(config, types, node_a)
    node_b = BeaconChain(config, types, state.copy())
    return config, types, node_a, node_b


def test_range_sync_catches_up(two_nodes):
    config, types, node_a, node_b = two_nodes
    peer = LocalPeer("nodeA", ReqRespHandlers(config, types, node_a), types)
    status = peer.status()
    assert status.head_slot == 2 * SPE

    node_b.clock.set_slot(2 * SPE)
    rs = RangeSync(node_b, types, SPE, verify_signatures=False)
    rs.add_peer(peer)
    head = rs.sync_to(int(status.head_slot))
    assert head == 2 * SPE
    assert node_b.head_root == node_a.head_root
    assert (
        node_b.head_state.state.hash_tree_root()
        == node_a.head_state.state.hash_tree_root()
    )


def test_range_sync_no_peers_fails(two_nodes):
    config, types, node_a, _ = two_nodes
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    fresh = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    node_c = BeaconChain(config, types, fresh)
    rs = RangeSync(node_c, types, SPE)
    with pytest.raises(RangeSyncError):
        rs.sync_to(4)


def test_unknown_block_sync_resolves_parents(two_nodes):
    config, types, node_a, _ = two_nodes
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    fresh = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    node_c = BeaconChain(config, types, fresh)
    node_c.clock.set_slot(2 * SPE)
    peer = LocalPeer("nodeA", ReqRespHandlers(config, types, node_a), types)

    # hand node_c a mid-chain block whose ancestors it lacks
    target = node_a.blocks[
        node_a.fork_choice.get_ancestor(node_a.head_root, 5)
    ]
    ub = UnknownBlockSync(node_c, types)
    ub.add_peer(peer)
    root = ub.resolve(target, verify_signatures=False)
    assert root in node_c.blocks
    assert node_c.head_state.state.slot >= 5


def test_segment_import_batches_signatures_once():
    """process_block_segment verifies ALL of a segment's signature sets in
    one verifier call (reference verifyBlocksSignatures batches ~8k sigs
    per 64-block segment) and imports nothing when the batch fails."""
    from tests.test_chain import _attest_head, _sign_block, _sk
    from lodestar_tpu.bls import api as bls
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.params import DOMAIN_RANDAO
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.state_transition import interop_genesis_state, process_slots
    from lodestar_tpu.state_transition.block import _epoch_signing_root
    from lodestar_tpu.types import get_types

    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(fork_config, types, 16, genesis_time=1_600_000_000)
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )

    # producer chain builds a 6-block segment
    producer = BeaconChain(config, types, state.copy())
    segment = []
    for slot in range(1, 7):
        producer.clock.set_slot(slot)
        trial = producer.head_state.copy()
        if slot > trial.state.slot:
            process_slots(trial, types, slot)
        proposer = trial.epoch_ctx.get_beacon_proposer(slot)
        reveal = _sk(proposer).sign(
            _epoch_signing_root(slot // MINIMAL.SLOTS_PER_EPOCH,
                                config.get_domain(DOMAIN_RANDAO, slot))
        ).to_bytes()
        block = producer.produce_block(slot, randao_reveal=reveal)
        signed = _sign_block(config, types, block)
        producer.process_block(signed, verify_signatures=False)
        segment.append(signed)

    class CountingVerifier:
        calls = 0

        def verify_signature_sets(self, sets):
            CountingVerifier.calls += 1
            return bls.verify_signature_sets(list(sets))

        def verify_signature_sets_individual(self, sets):
            return [bls.verify_signature_sets([s]) for s in sets]

    importer = BeaconChain(
        config, types, state.copy(), verifier=CountingVerifier()
    )
    importer.clock.set_slot(6)
    roots = importer.process_block_segment(segment, verify_signatures=True)
    assert len(roots) == 6
    assert CountingVerifier.calls == 1  # the whole segment in ONE dispatch
    assert importer.head_root == roots[-1]

    # a tampered segment imports NOTHING
    bad_segment = [s.copy() for s in segment]
    bad_segment[3].signature = b"\x11" * 96
    importer2 = BeaconChain(config, types, state.copy())
    importer2.clock.set_slot(6)
    import pytest as _pytest

    from lodestar_tpu.chain.chain import BlockImportError

    with _pytest.raises(BlockImportError) as ei:
        importer2.process_block_segment(bad_segment, verify_signatures=True)
    assert importer2.head_state.state.slot == 0
    # round 6: the failure names the offending block (per-set verdicts —
    # bisection on the device tier — pinpoint it instead of an opaque
    # whole-segment failure); the tampered block sits at slot 4
    assert "slot" in str(ei.value) and "4" in str(ei.value)


def test_range_sync_download_import_overlap(two_nodes):
    """VERDICT r3 #7: with a window of batches in flight, later batches
    must be DOWNLOADING while an earlier batch is PROCESSING — measured
    by interval overlap, not throughput luck. A slow-peer wrapper stamps
    each download span; the chain import is stamped via monkeypatched
    segment processing."""
    import time

    config, types, node_a, _ = two_nodes
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    fresh = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    node_d = BeaconChain(config, types, fresh)
    node_d.clock.set_slot(2 * SPE)

    dl_spans: list[tuple[int, float, float]] = []
    proc_spans: list[tuple[float, float]] = []

    class SlowPeer:
        def __init__(self, inner, delay):
            self._inner = inner
            self._delay = delay
            self.peer_id = inner.peer_id

        def status(self):
            return self._inner.status()

        def beacon_blocks_by_range(self, start_slot, count):
            t0 = time.monotonic()
            time.sleep(self._delay)  # wire latency the import should hide
            out = self._inner.beacon_blocks_by_range(start_slot, count)
            dl_spans.append((start_slot, t0, time.monotonic()))
            return out

        def beacon_blocks_by_root(self, roots):
            return self._inner.beacon_blocks_by_root(roots)

    inner = LocalPeer("nodeA", ReqRespHandlers(config, types, node_a), types)
    # 4-slot batches (half-epoch span) → 4 batches over the 2 produced
    # epochs, window 2: batch 3's download must start while batch 1
    # imports
    rs = RangeSync(
        node_d, types, SPE // 2, verify_signatures=False,
        epochs_per_batch=1, download_window=2,
    )
    # several slow peers so the window can download concurrently
    for i in range(3):
        rs.add_peer(SlowPeer(inner, delay=0.15))

    real_process = node_d.process_block_segment

    def stamped_process(blocks, **kw):
        t0 = time.monotonic()
        out = real_process(blocks, **kw)
        time.sleep(0.05)  # give the import span measurable width
        proc_spans.append((t0, time.monotonic()))
        return out

    node_d.process_block_segment = stamped_process
    head = rs.sync_to(2 * SPE)
    assert head == 2 * SPE
    assert node_d.head_root == node_a.head_root

    # ≥2 batches (2 epochs / EPOCHS_PER_BATCH-epoch batches ≥ 1)… the
    # overlap claim needs at least two download spans and one process span
    assert len(dl_spans) >= 2 and len(proc_spans) >= 1
    overlap = any(
        dl_start < p_end and p_start < dl_end
        for _, dl_start, dl_end in dl_spans
        for p_start, p_end in proc_spans
    )
    assert overlap, (dl_spans, proc_spans)


def test_range_sync_retries_with_rotation_under_window(two_nodes):
    """Peer rotation must survive the concurrent window: a peer that
    always fails is rotated away from, and the batch still completes."""
    config, types, node_a, _ = two_nodes
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    fresh = interop_genesis_state(fork_config, types, N, genesis_time=1_600_000_000)
    node_e = BeaconChain(config, types, fresh)
    node_e.clock.set_slot(2 * SPE)

    from lodestar_tpu.sync.peer import PeerError

    class FlakyPeer:
        peer_id = "flaky"

        def beacon_blocks_by_range(self, start_slot, count):
            raise PeerError("always down")

    good = LocalPeer("nodeA", ReqRespHandlers(config, types, node_a), types)
    rs = RangeSync(node_e, types, SPE, verify_signatures=False)
    rs.add_peer(FlakyPeer())
    rs.add_peer(good)
    assert rs.sync_to(2 * SPE) == 2 * SPE
    assert node_e.head_root == node_a.head_root
