"""Differential tests: device field tower (ops/fp2,fp6,fp12) vs CPU oracle.

Strategy mirrors the reference's use of known-answer + randomized checks for
blst (SURVEY.md §4.2): random elements from the oracle, push through the
device op (batched, jitted), pull back, compare exactly. All consensus math
must be bit-exact (SURVEY.md §7 hard part #8).
"""

import random

import jax
import numpy as np
import pytest

from lodestar_tpu.bls.fields import P, Fq, Fq2, Fq6, Fq12
from lodestar_tpu.ops import fp2 as jfp2
from lodestar_tpu.ops import fp6 as jfp6
from lodestar_tpu.ops import fp12 as jfp12
from lodestar_tpu.ops.io_host import (
    fq2_to_limbs,
    fq6_to_limbs,
    fq12_to_limbs,
    limbs_to_fq2,
    limbs_to_fq6,
    limbs_to_fq12,
)

rng = random.Random(0xF2F6F12)


def rand_fq2() -> Fq2:
    return Fq2(Fq(rng.randrange(P)), Fq(rng.randrange(P)))


def rand_fq6() -> Fq6:
    return Fq6(rand_fq2(), rand_fq2(), rand_fq2())


def rand_fq12() -> Fq12:
    return Fq12(rand_fq6(), rand_fq6())


BATCH = 4


def _batch(maker, to_limbs, n=BATCH):
    vals = [maker() for _ in range(n)]
    return vals, np.stack([to_limbs(v) for v in vals])


class TestFp2:
    def test_mul_square_inv(self):
        avals, a = _batch(rand_fq2, fq2_to_limbs)
        bvals, b = _batch(rand_fq2, fq2_to_limbs)
        got_mul = jax.jit(jfp2.mul)(a, b)
        got_sq = jax.jit(jfp2.square)(a)
        got_inv = jax.jit(jfp2.inv)(a)
        got_xi = jax.jit(jfp2.mul_by_xi)(a)
        for i in range(BATCH):
            assert limbs_to_fq2(got_mul[i]) == avals[i] * bvals[i]
            assert limbs_to_fq2(got_sq[i]) == avals[i].square()
            assert limbs_to_fq2(got_inv[i]) == avals[i].inverse()
            assert limbs_to_fq2(got_xi[i]) == avals[i] * Fq2.from_ints(1, 1)

    def test_add_sub_conj(self):
        avals, a = _batch(rand_fq2, fq2_to_limbs)
        bvals, b = _batch(rand_fq2, fq2_to_limbs)
        got_add = jax.jit(jfp2.add)(a, b)
        got_sub = jax.jit(jfp2.sub)(a, b)
        got_conj = jax.jit(jfp2.conj)(a)
        for i in range(BATCH):
            assert limbs_to_fq2(got_add[i]) == avals[i] + bvals[i]
            assert limbs_to_fq2(got_sub[i]) == avals[i] - bvals[i]
            assert limbs_to_fq2(got_conj[i]) == avals[i].conjugate()


class TestFp6:
    def test_mul_inv_mul_by_v(self):
        avals, a = _batch(rand_fq6, fq6_to_limbs)
        bvals, b = _batch(rand_fq6, fq6_to_limbs)
        got_mul = jax.jit(jfp6.mul)(a, b)
        got_v = jax.jit(jfp6.mul_by_v)(a)
        got_inv = jax.jit(jfp6.inv)(a)
        for i in range(BATCH):
            assert limbs_to_fq6(got_mul[i]) == avals[i] * bvals[i]
            assert limbs_to_fq6(got_v[i]) == avals[i].mul_by_v()
            assert limbs_to_fq6(got_inv[i]) == avals[i].inverse()


class TestFp12:
    def test_mul_square(self):
        avals, a = _batch(rand_fq12, fq12_to_limbs)
        bvals, b = _batch(rand_fq12, fq12_to_limbs)
        got_mul = jax.jit(jfp12.mul)(a, b)
        got_sq = jax.jit(jfp12.square)(a)
        for i in range(BATCH):
            assert limbs_to_fq12(got_mul[i]) == avals[i] * bvals[i]
            assert limbs_to_fq12(got_sq[i]) == avals[i].square()

    def test_inv_conj(self):
        avals, a = _batch(rand_fq12, fq12_to_limbs)
        got_inv = jax.jit(jfp12.inv)(a)
        got_conj = jax.jit(jfp12.conj)(a)
        for i in range(BATCH):
            assert limbs_to_fq12(got_inv[i]) == avals[i].inverse()
            assert limbs_to_fq12(got_conj[i]) == avals[i].conjugate()

    @pytest.mark.parametrize("power", [1, 2, 3])
    def test_frobenius(self, power):
        avals, a = _batch(rand_fq12, fq12_to_limbs)
        got = jax.jit(jfp12.frobenius, static_argnums=1)(a, power)
        for i in range(BATCH):
            assert limbs_to_fq12(got[i]) == avals[i].frobenius(power)

    def test_mul_by_line(self):
        avals, a = _batch(rand_fq12, fq12_to_limbs)
        l0v, l0 = _batch(rand_fq2, fq2_to_limbs)
        l1v, l1 = _batch(rand_fq2, fq2_to_limbs)
        l2v, l2 = _batch(rand_fq2, fq2_to_limbs)
        got = jax.jit(jfp12.mul_by_line)(a, l0, l1, l2)
        for i in range(BATCH):
            # line = l0 + l1·w² + l2·w³ as a full Fq12 element
            line = Fq12(
                Fq6(l0v[i], l1v[i], Fq2.zero()),
                Fq6(Fq2.zero(), l2v[i], Fq2.zero()),
            )
            assert limbs_to_fq12(got[i]) == avals[i] * line

    def test_one_is_one(self):
        one = jfp12.one((2,))
        assert bool(jfp12.is_one(one).all())
        _, a = _batch(rand_fq12, fq12_to_limbs, n=2)
        assert not bool(jfp12.is_one(a).any())
