"""Network facade end-to-end: three real nodes over TCP — mesh forms,
a published block propagates two hops and is imported by every chain,
invalid gossip is rejected and scored.

Reference analog: `beacon-node/test/e2e/network/` (real libp2p between
in-process nodes) + sim assertions on head advancement.
"""

import asyncio

import pytest

# live networking (noise transport identities) needs the `cryptography`
# wheel, which minimal CI images may lack — skip, not error
pytest.importorskip("cryptography")

from lodestar_tpu.network.network import Network
from lodestar_tpu.network.transport import NodeIdentity

# deep-kernel compiles / subprocess e2e: excluded from the default fast
# suite (VERDICT round-1 weakness #4); run with `pytest -m slow` or -m ""
pytestmark = pytest.mark.slow



def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120.0))


_genesis_cache = {}


def _fresh_chain():
    """A chain on the shared interop genesis — genesis construction does 16
    real BLS deposit verifications (~seconds each on the CPU oracle), so it
    is built once per process and copied per node."""
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.state_transition import interop_genesis_state
    from lodestar_tpu.types import get_types

    if not _genesis_cache:
        types = get_types(MINIMAL).phase0
        fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
        state = interop_genesis_state(
            fork_config, types, 16, genesis_time=1_600_000_000
        )
        config = BeaconConfig(
            MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
        )
        _genesis_cache["v"] = (config, types, state)
    config, types, state = _genesis_cache["v"]
    return config, types, BeaconChain(config, types, state.copy())


def _produce_signed_block(config, types, chain, slot):
    from lodestar_tpu.params import DOMAIN_RANDAO
    from lodestar_tpu.state_transition import process_slots
    from lodestar_tpu.state_transition.block import _epoch_signing_root
    from tests.test_chain import _sign_block, _sk

    chain.clock.set_slot(slot)
    trial = chain.head_state.copy()
    if slot > trial.state.slot:
        process_slots(trial, types, slot)
    proposer = trial.epoch_ctx.get_beacon_proposer(slot)
    reveal = _sk(proposer).sign(
        _epoch_signing_root(0, config.get_domain(DOMAIN_RANDAO, slot))
    ).to_bytes()
    block = chain.produce_block(slot, randao_reveal=reveal)
    return _sign_block(config, types, block)


async def _bring_up(n=3):
    nets = []
    for i in range(n):
        config, types, chain = _fresh_chain()
        net = Network(
            config,
            types,
            chain,
            identity=NodeIdentity.from_seed(bytes([i])),
            verify_signatures=False,
        )
        await net.start()
        nets.append(net)
    # line topology: 0-1, 1-2, ... (propagation must cross hops)
    for i in range(n - 1):
        await nets[i].connect(*nets[i + 1].transport.listen_addr)
    # let subscriptions flow and meshes form
    for _ in range(3):
        await asyncio.sleep(0.05)
        for net in nets:
            await net.gossip.heartbeat()
    return nets


def test_block_propagates_and_imports_across_three_nodes():
    async def main():
        nets = await _bring_up(3)
        try:
            a = nets[0]
            signed = _produce_signed_block(a.config, a.types, a.chain, 1)
            for net in nets[1:]:
                net.chain.clock.set_slot(1)
            a.chain.process_block(signed, verify_signatures=False)
            sent = await a.publish_block(signed)
            assert sent >= 1
            root = signed.message.hash_tree_root()
            # wait for HEAD convergence, not just block presence: has_block
            # flips mid-import, before update_head finishes on that node
            for _ in range(200):
                if all(net.chain.head_root == root for net in nets):
                    break
                await asyncio.sleep(0.05)
            for net in nets:
                assert net.chain.fork_choice.has_block(root), "block not imported"
                assert net.chain.head_root == root
        finally:
            for net in nets:
                await net.stop()

    run(main())


def test_status_handshake_populates_peer_manager():
    async def main():
        nets = await _bring_up(2)
        try:
            await asyncio.sleep(0.2)
            a, b = nets
            info = a.peer_manager.peers.get(b.peer_id)
            assert info is not None
            for _ in range(50):
                if info.status is not None:
                    break
                await asyncio.sleep(0.05)
            assert info.status is not None
            assert int(info.status.head_slot) == b.chain.head_state.state.slot
        finally:
            for net in nets:
                await net.stop()

    run(main())


def test_invalid_block_rejected_not_forwarded_and_scored():
    async def main():
        nets = await _bring_up(3)
        try:
            a, b, c = nets
            # a broken "block": random bytes that snappy-decode but fail SSZ
            from lodestar_tpu.network.gossip.encoding import encode_message
            from lodestar_tpu.network.gossip.topic import (
                GossipTopic,
                GossipType,
                stringify_topic,
            )

            digest = a.config.fork_digest("phase0")
            topic = stringify_topic(GossipTopic(GossipType.beacon_block, digest))
            wire = encode_message(b"\x01\x02\x03-not-a-block")
            await a.gossip.publish(topic, wire)
            await asyncio.sleep(0.3)
            # b rejected: scored against a, nothing reached c
            assert b.gossip.score.score(a.peer_id) < 0
            assert c.gossip.score.score(b.peer_id) >= 0
        finally:
            for net in nets:
                await net.stop()

    run(main())


def test_attestation_gossip_reaches_pool():
    async def main():
        nets = await _bring_up(2)
        try:
            a, b = nets
            # craft a minimal valid single-bit attestation on the head
            from lodestar_tpu.chain.validation import compute_subnet_for_attestation
            from tests.test_network_gossip import _make_single_attestation

            a.chain.clock.set_slot(1)
            b.chain.clock.set_slot(1)
            att, _signer = _make_single_attestation(a.config, a.types, a.chain)
            ctx = a.chain.head_state.epoch_ctx
            subnet = compute_subnet_for_attestation(ctx, 0, 0, a.config.preset)
            await b.subscribe_subnet(subnet)
            await a.subscribe_subnet(subnet)
            # wait until b's subnet subscription has reached a
            for _ in range(100):
                peer = a.gossip.peers.get(b.peer_id)
                if peer is not None and any(
                    "beacon_attestation" in t for t in peer.topics
                ):
                    break
                await asyncio.sleep(0.05)
            for _ in range(2):
                await a.gossip.heartbeat()
                await b.gossip.heartbeat()
            sent = await a.publish_attestation(att, subnet)
            assert sent >= 1
            for _ in range(100):
                if len(b.chain.attestation_pool._by_slot.get(0, {})) > 0:
                    break
                await asyncio.sleep(0.05)
            assert len(b.chain.attestation_pool._by_slot.get(0, {})) > 0
        finally:
            for net in nets:
                await net.stop()

    run(main())
