"""utils/retry.py: the shared jittered-exponential-backoff helper
(round-7 satellite) and the clients migrated onto it (eth1 provider,
engine client, external signer, json_http_request)."""

import pytest

from lodestar_tpu.utils.retry import RetryPolicy, retry_call, transient_http


def _policy(**kw):
    kw.setdefault("sleep", lambda s: None)
    return RetryPolicy(**kw)


def test_succeeds_after_transient_failures():
    calls = []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("reset")
        return "ok"

    assert retry_call(fn, policy=_policy(max_attempts=3)) == "ok"
    assert len(calls) == 3


def test_exhausted_attempts_reraise_last_error():
    calls = []

    def fn():
        calls.append(1)
        raise OSError(f"boom {len(calls)}")

    with pytest.raises(OSError, match="boom 2"):
        retry_call(fn, policy=_policy(max_attempts=2))
    assert len(calls) == 2


def test_non_retryable_raises_immediately():
    calls = []

    def fn():
        calls.append(1)
        raise ValueError("bad input")

    policy = _policy(
        max_attempts=5, retryable=lambda e: isinstance(e, OSError)
    )
    with pytest.raises(ValueError):
        retry_call(fn, policy=policy)
    assert len(calls) == 1


def test_on_error_fires_for_every_failed_attempt():
    seen = []

    def fn():
        raise OSError("x")

    with pytest.raises(OSError):
        retry_call(
            fn,
            policy=_policy(max_attempts=3),
            on_error=lambda e, attempt, will_retry: seen.append(
                (attempt, will_retry)
            ),
        )
    # the final attempt reports will_retry=False (the old ad-hoc loops
    # counted their error metric on every failure, including the last)
    assert seen == [(0, True), (1, True), (2, False)]


def test_backoff_doubles_capped_and_jittered():
    slept = []
    policy = RetryPolicy(
        max_attempts=5,
        base_delay_s=1.0,
        max_delay_s=3.0,
        jitter=0.25,
        sleep=slept.append,
        rand=lambda: 1.0,  # worst-case high jitter
    )

    def fn():
        raise OSError("x")

    with pytest.raises(OSError):
        retry_call(fn, policy=policy)
    # bases 1, 2, min(4,3)=3, min(8,3)=3 — each x1.25 at rand()=1.0
    assert slept == pytest.approx([1.25, 2.5, 3.75, 3.75])
    # rand()=0.0 gives the low edge; delays stay non-negative
    assert policy.delay_s(0) == 1.25
    policy.rand = lambda: 0.0
    assert policy.delay_s(0) == pytest.approx(0.75)


def test_zero_jitter_is_deterministic():
    policy = _policy(max_attempts=2, base_delay_s=0.5, jitter=0.0)
    assert policy.delay_s(0) == 0.5
    assert policy.delay_s(3) == 4.0


def test_transient_http_predicate():
    import http.client

    assert transient_http(OSError("reset"))
    assert transient_http(http.client.BadStatusLine("x"))
    assert not transient_http(RuntimeError("500: server said no"))


# --- migrated clients --------------------------------------------------------


def test_eth1_provider_retries_through_shared_helper(monkeypatch):
    """Eth1ProviderHttp._call: two transport failures then success — the
    shared policy must deliver the result and count every error."""
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.eth1.provider import Eth1ProviderHttp

    provider = Eth1ProviderHttp(
        MINIMAL_CHAIN_CONFIG, None, "127.0.0.1", 1,
        retries=3, retry_delay=0.0,
    )
    calls = []

    def flaky(method, params):
        calls.append(method)
        if len(calls) < 3:
            raise OSError("connection refused")
        return "0x10"

    monkeypatch.setattr(provider, "_call_once", flaky)
    assert provider._call("eth_blockNumber", []) == "0x10"
    assert len(calls) == 3


def test_eth1_provider_wraps_final_error(monkeypatch):
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.eth1.provider import Eth1ProviderHttp

    provider = Eth1ProviderHttp(
        MINIMAL_CHAIN_CONFIG, None, "127.0.0.1", 1,
        retries=2, retry_delay=0.0,
    )
    monkeypatch.setattr(
        provider, "_call_once",
        lambda m, p: (_ for _ in ()).throw(OSError("down")),
    )
    with pytest.raises(RuntimeError, match="failed after retries"):
        provider._call("eth_blockNumber", [])


def test_json_http_request_retries_transport_only(monkeypatch):
    """retries>0 re-issues on socket errors but NEVER on an HTTP error
    status (the server answered; replaying a non-idempotent request is
    the caller's call)."""
    import lodestar_tpu.utils.http as http_mod

    attempts = []

    class FakeResp:
        status = 503

        def read(self):
            return b'{"msg": "busy"}'

    class FakeConn:
        def __init__(self, *a, **kw):
            pass

        def request(self, *a, **kw):
            attempts.append(1)
            if len(attempts) == 1:
                raise OSError("reset by peer")

        def getresponse(self):
            return FakeResp()

        def close(self):
            pass

    monkeypatch.setattr(http_mod.http.client, "HTTPConnection", FakeConn)
    from lodestar_tpu.utils.retry import RetryPolicy, transient_http

    policy = RetryPolicy(
        max_attempts=4, base_delay_s=0.0, sleep=lambda s: None,
        retryable=transient_http,
    )
    # attempt 1: OSError (retried); attempt 2: HTTP 503 -> error_cls raised,
    # NOT retried despite attempts remaining
    with pytest.raises(RuntimeError, match="503"):
        http_mod.json_http_request(
            "h", 1, "GET", "/x", retry_policy=policy
        )
    assert len(attempts) == 2
