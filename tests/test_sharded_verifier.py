"""Multi-chip sharded verification on the 8-device virtual CPU mesh
(VERDICT r3 weak #4 / next-round #3: the `cpu_mesh` fixture finally has
consumers).

Covers the ICI tier (`parallel/sharded.py`): verdict parity with the
single-device kernels on identical batches, one-invalid-lane rejection
through `shard_map`, batches that do not fill the lane grid (padding
lanes cross chip boundaries), and the grouped (shared-signing-root)
variant. Shapes are deliberately tiny — the point is the collective
path, not throughput (tools/mesh_scaling.py measures that).
"""

import numpy as np
import pytest

from lodestar_tpu.bls import api as bls
from lodestar_tpu.parallel.sharded import (
    ShardedBlsVerifier,
    ShardedGroupedVerifier,
)
from lodestar_tpu.parallel.verifier import (
    TpuBlsVerifier,
    _rand_bits,
    _rand_pairs,
)

pytestmark = pytest.mark.slow

_COUNTER = [0]


def _det_rng():
    _COUNTER[0] += 1
    return (0x9E3779B97F4A7C15 * _COUNTER[0]) & ((1 << 64) - 1)


def _make_sets(n, salt=0, root=None):
    sets = []
    for i in range(n):
        sk = bls.interop_secret_key(i + salt)
        msg = root if root is not None else bytes([i ^ 0xA5]) * 32
        sets.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return sets


def _tamper(sets, idx):
    wrong = bls.interop_secret_key(99)
    sets = list(sets)
    sets[idx] = bls.SignatureSet(
        pubkey=sets[idx].pubkey,
        message=sets[idx].message,
        signature=wrong.sign(sets[idx].message).to_bytes(),
    )
    return sets


@pytest.fixture(scope="module")
def host():
    """Single-device verifier used only for its marshalling (wire bytes →
    limb arrays) and as the parity oracle."""
    return TpuBlsVerifier(buckets=(16,), rng=_det_rng,
                          grouped_configs=((8, 4),))


def test_sharded_parity_with_single_device(cpu_mesh, host):
    sharded = ShardedBlsVerifier(cpu_mesh, lanes_per_chip=2)
    sets = _make_sets(16)
    arrs = host._marshal(sets)
    assert arrs is not None
    r_bits = _rand_bits(16, host._rng)
    assert bool(host.kernels.verify_batch(arrs, r_bits))
    assert sharded.verify_arrays(arrs, r_bits) is True

    bad = host._marshal(_tamper(sets, 5))
    assert bool(host.kernels.verify_batch(bad, r_bits)) is False
    assert sharded.verify_arrays(bad, r_bits) is False


def test_sharded_invalid_lane_on_any_chip(cpu_mesh, host):
    """The tampered lane must be caught wherever it lands in the shard
    grid — first chip, middle, and the last chip's last lane."""
    sharded = ShardedBlsVerifier(cpu_mesh, lanes_per_chip=2)
    sets = _make_sets(16)
    r_bits = _rand_bits(16, host._rng)
    for idx in (0, 7, 15):
        bad = host._marshal(_tamper(sets, idx))
        assert sharded.verify_arrays(bad, r_bits) is False, idx


def test_sharded_partial_batch_padding(cpu_mesh, host):
    """n < lane grid: padding lanes (valid=False) span whole chips — the
    masked-to-infinity convention must hold across shard boundaries."""
    sharded = ShardedBlsVerifier(cpu_mesh, lanes_per_chip=2)
    sets = _make_sets(5)
    arrs = host._marshal(sets)  # bucket 16 → 11 padding lanes
    assert arrs is not None and arrs.n == 5
    r_bits = _rand_bits(16, host._rng)
    assert sharded.verify_arrays(arrs, r_bits) is True
    bad = host._marshal(_tamper(sets, 4))
    assert sharded.verify_arrays(bad, r_bits) is False


def test_sharded_grouped_parity_and_rejection(cpu_mesh, host):
    """Grouped tier: 8 root-rows × 4 lanes over 8 chips (1 row each);
    verdict parity with the single-device grouped kernel and rejection
    of a tampered lane."""
    sharded = ShardedGroupedVerifier(cpu_mesh)
    # two committees, shared root within each → groups well
    sets = _make_sets(8, root=b"\x42" * 32) + _make_sets(
        8, salt=20, root=b"\x43" * 32
    )
    plan = host._plan_groups(sets)
    assert plan is not None
    g = host._marshal_grouped(sets, plan)
    assert g is not None
    a_bits, b_bits = _rand_pairs(g.valid.shape, _det_rng)
    assert bool(host.kernels.verify_grouped(g, a_bits, b_bits))
    assert sharded.verify_grouped(g, a_bits, b_bits) is True

    bad_sets = _tamper(sets, 3)
    gb = host._marshal_grouped(bad_sets, host._plan_groups(bad_sets))
    assert gb is not None
    assert bool(host.kernels.verify_grouped(gb, a_bits, b_bits)) is False
    assert sharded.verify_grouped(gb, a_bits, b_bits) is False


def test_sharded_grouped_refuses_non_dividing_mesh():
    """A mesh that does not divide the 64 constant lanes must refuse
    loudly (silent lane-dropping would reject every batch)."""
    import jax
    from jax.sharding import Mesh

    from lodestar_tpu.parallel.sharded import make_sharded_grouped_verifier

    devices = np.array(jax.devices("cpu")[:6])
    if len(devices) < 6:
        pytest.skip("needs 6 virtual devices")
    mesh = Mesh(devices.reshape(6), axis_names=("dp",))
    with pytest.raises(ValueError, match="must divide"):
        make_sharded_grouped_verifier(mesh)


def test_sharded_pk_grouped_parity_and_rejection(cpu_mesh):
    """PK-grouped tier (round 7): 8 pubkey-rows × 4 messages over 8 chips
    (1 row each); verdict parity with the single-device pk-grouped kernel
    and rejection of a tampered lane."""
    from lodestar_tpu.parallel.sharded import ShardedPkGroupedVerifier

    host = TpuBlsVerifier(buckets=(16,), rng=_det_rng,
                          pk_grouped_configs=((8, 4),))
    sharded = ShardedPkGroupedVerifier(cpu_mesh)
    # 8 signers × 4 distinct messages each → groups by pubkey
    sets = []
    for i in range(8):
        sk = bls.interop_secret_key(i)
        for j in range(4):
            msg = bytes([0x10 * i + j]) * 32
            sets.append(bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            ))
    plan = host._plan_pk_groups(sets)
    assert plan is not None
    g = host._marshal_pk_grouped(sets, plan)
    assert g is not None
    a_bits, b_bits = _rand_pairs(g.valid.shape, _det_rng)
    assert bool(host.kernels.verify_pk_grouped(g, a_bits, b_bits))
    assert bool(sharded.submit(g, a_bits, b_bits)) is True

    bad_sets = _tamper(sets, 13)
    gb = host._marshal_pk_grouped(bad_sets, host._plan_pk_groups(bad_sets))
    assert gb is not None
    assert bool(host.kernels.verify_pk_grouped(gb, a_bits, b_bits)) is False
    assert bool(sharded.submit(gb, a_bits, b_bits)) is False


def test_sharded_bisect_parity_and_verdict_vector(cpu_mesh, host):
    """Bisection tier (round 7): the sharded tree must hand back the SAME
    root verdict and a `levels` pyramid the host bisection search walks
    to the same per-set verdict vector as the single-device kernel."""
    from lodestar_tpu.parallel.sharded import ShardedBisectVerifier

    sharded = ShardedBisectVerifier(cpu_mesh)
    sets = _make_sets(16)
    arrs = host._marshal(sets)
    assert arrs is not None
    r_bits = _rand_bits(16, host._rng)

    root_ref, _ = host.kernels.verify_bisect_tree(arrs, r_bits)
    root_sh, _ = sharded.submit(arrs, r_bits)
    assert bool(root_ref) is True and bool(root_sh) is True

    # two invalid lanes on different chips: root fails both ways and the
    # host bisection over the SHARDED levels finds exactly those lanes
    bad = host._marshal(_tamper(_tamper(sets, 3), 12))
    root_ref, lv_ref = host.kernels.verify_bisect_tree(bad, r_bits)
    root_sh, lv_sh = sharded.submit(bad, r_bits)
    assert bool(root_ref) is False and bool(root_sh) is False
    v_ref = host._bisect(bad, lv_ref)
    v_sh = host._bisect(bad, lv_sh)
    assert list(v_sh[:16]) == list(v_ref[:16])
    assert [i for i, ok in enumerate(v_sh[:16]) if not ok] == [3, 12]
