"""Epoch-resident crypto (ISSUE 18): the `EpochPubkeyTable` LRU /
eviction / device-OOM-fallback contract, the `_pk_rows` table consult,
and the lane dispatcher's H(msg) dedup pre-warm.

Everything host-side — table bookkeeping and marshal-path lookups run
without any kernel dispatch, so the file stays in the fast tier. The
fused-pairing differential twins live in tests/test_pallas_tower.py.
"""

import threading

import numpy as np
import pytest

from lodestar_tpu import native
from lodestar_tpu.bls import api as bls
from lodestar_tpu.observability.stages import PipelineMetrics
from lodestar_tpu.parallel.epoch_table import ROW_WIDTH, EpochPubkeyTable

needs_native = pytest.mark.skipif(
    not native.HAVE_NATIVE_BLS, reason="native BLS tier unavailable"
)


def _rows(n, start=0):
    return [
        (bytes([start + i]) * 48, np.full(ROW_WIDTH, start + i, np.int32))
        for i in range(n)
    ]


def _table(**kw):
    kw.setdefault("epochs", 2)
    kw.setdefault("max_rows", 64)
    kw.setdefault("observer", PipelineMetrics())
    return EpochPubkeyTable(**kw)


def _sets(n, shared_root=True, salt=0):
    out = []
    for i in range(n):
        sk = bls.interop_secret_key(i + salt)
        msg = (
            b"\x42" * 32
            if shared_root
            else bytes([i & 0xFF, salt & 0xFF]) + b"\x17" * 30
        )
        out.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=sk.sign(msg).to_bytes(),
            )
        )
    return out


# --- table bookkeeping -------------------------------------------------------


def test_lru_rotation_over_two_epochs():
    t = _table(epochs=2)
    assert t.populate(0, _rows(4)) == 4
    assert t.populate(1, _rows(2, start=10)) == 2
    # both retained; populating a third epoch evicts the oldest
    assert [e["epoch"] for e in t.snapshot()["entries"]] == [0, 1]
    t.populate(2, _rows(3, start=20))
    snap = t.snapshot()
    assert [e["epoch"] for e in snap["entries"]] == [1, 2]
    assert snap["evictions"] == 4  # epoch 0's rows
    # evicted epoch's keys no longer resolve; retained ones do
    assert t.lookup_rows([bytes([0]) * 48]) == [None]
    hit = t.lookup_rows([bytes([10]) * 48])[0]
    assert hit is not None and hit[0] == 10


def test_repopulating_same_epoch_replaces_not_rotates():
    t = _table(epochs=2)
    t.populate(0, _rows(4))
    t.populate(1, _rows(4, start=10))
    t.populate(1, _rows(2, start=50))  # validator set changed mid-epoch
    snap = t.snapshot()
    assert [e["epoch"] for e in snap["entries"]] == [0, 1]
    assert t.lookup_rows([bytes([10]) * 48]) == [None]
    assert t.lookup_rows([bytes([50]) * 48])[0] is not None


def test_row_cap_truncation_counts_as_evictions():
    t = _table(max_rows=3)
    assert t.populate(0, _rows(5)) == 3
    snap = t.snapshot()
    assert snap["total_rows"] == 3
    assert snap["evictions"] == 2  # the truncated tail


def test_occupancy_and_hit_miss_metrics():
    pm = PipelineMetrics()
    t = _table(observer=pm)
    t.populate(0, _rows(3))
    t.lookup_rows([bytes([0]) * 48, bytes([1]) * 48, bytes([99]) * 48])
    assert [int(v) for _, v in pm.epoch_table_hits.collect()] == [2]
    assert [int(v) for _, v in pm.epoch_table_misses.collect()] == [1]
    assert [int(v) for _, v in pm.epoch_table_occupancy_gauge.collect()] == [3]
    t.populate(1, _rows(2, start=10))
    t.populate(2, _rows(2, start=20))  # rotates epoch 0 out
    assert [int(v) for _, v in pm.epoch_table_evictions.collect()] == [3]


def test_device_put_failure_degrades_to_host_only(monkeypatch):
    import jax

    def _oom(*a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    monkeypatch.setattr(jax, "device_put", _oom)
    t = _table()
    assert t.populate(0, _rows(4)) == 4  # population must not raise
    snap = t.snapshot()
    assert snap["device_put_failures"] == 1
    assert snap["entries"][0]["device_resident"] is False
    # host-mirror lookups keep serving
    assert t.lookup_rows([bytes([2]) * 48])[0] is not None
    # device gather reports unavailable instead of raising
    assert t.gather_device(0, [0]) is None


def test_gather_kernel_is_ledger_wrapped():
    t = _table()
    t.populate(0, _rows(4))
    out = t.gather_device(0, np.arange(2))
    if out is None:
        pytest.skip("no device available for the gather")
    assert np.asarray(out).shape == (2, ROW_WIDTH)
    assert t._gather.__compile_ledger_kernel__ == "epoch_table"


def test_concurrent_populate_and_lookup():
    t = _table(epochs=2)
    stop = threading.Event()
    errors = []

    def reader():
        keys = [bytes([i]) * 48 for i in range(8)]
        while not stop.is_set():
            try:
                t.lookup_rows(keys)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    threads = [threading.Thread(target=reader, daemon=True) for _ in range(4)]
    for th in threads:
        th.start()
    for epoch in range(12):
        t.populate(epoch, _rows(8, start=epoch % 4))
    stop.set()
    for th in threads:
        th.join(timeout=5.0)
    assert not errors
    assert len(t.snapshot()["entries"]) == 2


# --- verifier integration ----------------------------------------------------


@needs_native
def test_pk_rows_served_from_table_without_decompress(monkeypatch):
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    v = TpuBlsVerifier(buckets=(4,))
    assert v._epoch_table is not None  # default-on
    sets = _sets(3)
    ref = v._pk_rows(sets)  # decompress path fills _pk_cache
    assert ref is not None
    assert v.epoch_table_populate(7, [s.pubkey.to_bytes() for s in sets]) == 3
    v._pk_cache.clear()

    def _no_decompress(*a, **k):  # pragma: no cover - must not be reached
        raise AssertionError("table hit should skip the C-tier decompress")

    monkeypatch.setattr(native, "bls_g1_decompress", _no_decompress)
    out = v._pk_rows(sets)
    assert out is not None
    assert np.array_equal(out[0], ref[0]) and np.array_equal(out[1], ref[1])


@needs_native
def test_pk_rows_falls_back_to_decompress_on_table_miss():
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    v = TpuBlsVerifier(buckets=(4,))
    v.epoch_table_populate(7, [s.pubkey.to_bytes() for s in _sets(2, salt=90)])
    sets = _sets(3)  # none of these in the table
    out = v._pk_rows(sets)
    assert out is not None and out[0].shape == (3, 32)


@needs_native
def test_device_oom_populate_still_serves_marshal_path(monkeypatch):
    """The OOM fallback chain: device_put fails -> host mirror serves
    `_pk_rows` -> and with the table fully gone the bounded `_pk_cache`
    still covers repeat keys."""
    import jax

    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    monkeypatch.setattr(
        jax, "device_put",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("oom")),
    )
    v = TpuBlsVerifier(buckets=(4,))
    sets = _sets(3, salt=40)
    assert v.epoch_table_populate(3, [s.pubkey.to_bytes() for s in sets]) == 3
    assert v.epoch_table_snapshot()["device_put_failures"] == 1
    v._pk_cache.clear()
    out = v._pk_rows(sets)  # host mirror
    assert out is not None
    v._epoch_table = None  # table lost entirely
    out2 = v._pk_rows(sets)  # _pk_cache (filled by the table hit above)
    assert out2 is not None
    assert np.array_equal(out[0], out2[0])


@needs_native
def test_epoch_table_knob_off(monkeypatch):
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    monkeypatch.setenv("LODESTAR_TPU_EPOCH_TABLE", "0")
    v = TpuBlsVerifier(buckets=(4,))
    assert v._epoch_table is None
    assert v.epoch_table_snapshot() == {"enabled": False}
    assert v.epoch_table_populate(0, [b"\x00" * 48]) == 0
    sets = _sets(2)
    assert v._pk_rows(sets) is not None  # plain _pk_cache path


@needs_native
def test_populate_skips_malformed_keys():
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    v = TpuBlsVerifier(buckets=(4,))
    good = [s.pubkey.to_bytes() for s in _sets(2)]
    assert v.epoch_table_populate(1, good + [b"\xff" * 48]) == 2


# --- dispatcher H(msg) dedup -------------------------------------------------


class _WarmRecorder:
    """Mock verifier with the `warm_h2c` seam: records pre-warm calls."""

    def __init__(self):
        self.result = True
        self.warm_calls: list[set] = []

    def verify_signature_sets(self, sets) -> bool:
        return True

    def verify_signature_sets_individual(self, sets):
        return [True] * len(sets)

    def warm_h2c(self, messages) -> int:
        self.warm_calls.append(set(messages))
        return len(messages)


class _Set:
    def __init__(self, message):
        self.message = message


def _dispatcher(verifier, **kw):
    from lodestar_tpu.chain.dispatcher import BlsLaneDispatcher

    kw.setdefault("max_sigs", 32)
    kw.setdefault("max_wait_ms", 50)
    kw.setdefault("workers", 1)
    kw.setdefault("pending_cap", 0)
    kw.setdefault("lane_caps", {})
    kw.setdefault("pipeline", PipelineMetrics())
    return BlsLaneDispatcher(verifier, **kw)


def test_dispatcher_dedups_h2c_across_coalesced_sets():
    v = _WarmRecorder()
    pm = PipelineMetrics()
    d = _dispatcher(v, pipeline=pm)
    try:
        a, b = b"\xaa" * 32, b"\xbb" * 32
        sets = [_Set(a), _Set(a), _Set(b), _Set(a)]
        assert d.verify_signature_sets(sets, lane="aggregate")
        assert v.warm_calls == [{a, b}]  # one hash per UNIQUE root
        assert [int(x) for _, x in pm.h2c_dedup_counter.collect()] == [2]
    finally:
        d.close()


def test_dispatcher_dedup_knob_off(monkeypatch):
    monkeypatch.setenv("LODESTAR_TPU_H2C_DEDUP", "0")
    v = _WarmRecorder()
    d = _dispatcher(v)
    try:
        assert d.verify_signature_sets([_Set(b"\xaa" * 32)], lane="aggregate")
        assert v.warm_calls == []
    finally:
        d.close()


def test_dispatcher_dedup_skips_verifiers_without_seam():
    from lodestar_tpu.chain.bls_verifier import MockBlsVerifier

    d = _dispatcher(MockBlsVerifier())
    try:
        # mock sets are plain strings (no .message): dedup must no-op
        assert d.verify_signature_sets(["a1", "a2"], lane="attestation")
    finally:
        d.close()


def _stub_kernels(verifier, verdict=True):
    """Replace every device dispatch with a constant verdict (shapes and
    marshalling still run for real — the dedup claim is about the HOST
    path, which feeds the kernels identical limbs either way)."""
    k = verifier.kernels
    ret = lambda *a, **kw: np.bool_(verdict)
    k.verify_batch = ret
    k.verify_batch_raw = ret
    k.verify_grouped = ret
    k.verify_grouped_raw = ret
    k.verify_pk_grouped = ret
    k.verify_pk_grouped_raw = ret
    k.verify_individual = lambda arrs, *a, **kw: np.full(
        arrs.valid.shape, verdict
    )


@needs_native
def test_dedup_verdicts_bit_identical_on_off(monkeypatch):
    """The dedup pre-warm only pre-fills the SAME `_h2c_cache` the
    marshal path fills on demand, so verdicts (and the underlying H(m)
    limbs the kernels receive) must be bit-identical with dedup on or
    off. Kernels are stubbed at the BatchVerifier seam — dedup changes
    nothing device-side by construction; the host marshal is the claim."""
    from lodestar_tpu.parallel.verifier import TpuBlsVerifier

    cold = TpuBlsVerifier(buckets=(4,))
    warm = TpuBlsVerifier(buckets=(4,))
    msg = b"\x42" * 32
    assert warm.warm_h2c([msg, msg, msg]) == 1  # one hash for three refs
    hx_cold = cold._hash_root(msg)
    hx_warm = warm._hash_root(msg)  # cache hit from the pre-warm
    assert np.array_equal(hx_cold[0], hx_warm[0])
    assert np.array_equal(hx_cold[1], hx_warm[1])
    # dispatcher-level parity: same sets through dedup on vs off, spying
    # on every H(m) limb pair the marshal path resolves
    results = {}
    for dedup in ("1", "0"):
        monkeypatch.setenv("LODESTAR_TPU_H2C_DEDUP", dedup)
        v = TpuBlsVerifier(buckets=(4,))
        _stub_kernels(v)
        hashes = []
        orig = v._hash_root

        def _spy(key, _orig=orig, _out=hashes):
            r = _orig(key)
            _out.append((key, r))
            return r

        v._hash_root = _spy
        d = _dispatcher(v)
        try:
            got = d.verify_signature_sets(_sets(3), lane="aggregate")
        finally:
            d.close()
        results[dedup] = (got, hashes)
    assert results["1"][0] == results["0"][0]
    on, off = results["1"][1], results["0"][1]
    limbs_on = {k: r for k, r in on if r is not None}
    limbs_off = {k: r for k, r in off if r is not None}
    assert set(limbs_on) == set(limbs_off)
    for k in limbs_on:
        assert np.array_equal(limbs_on[k][0], limbs_off[k][0])
        assert np.array_equal(limbs_on[k][1], limbs_off[k][1])
