"""Fault-tolerant BLS verification (ISSUE 4): the device supervisor's
failure policy — deadline, retry, CPU-oracle fallback, circuit breaker
with canary probes, negative-verdict audit — plus the fault-injection
seam, the waiter-timeout escape, and the /debug/breaker|faults control
surface.

Device kernels are STUBBED at the `BatchVerifier` seam (the
test_observability idiom) so the whole failure policy runs in the fast
suite; scripted fake verifiers drive the breaker state machine
deterministically."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from lodestar_tpu import native
from lodestar_tpu.bls import api as bls
from lodestar_tpu.chain.bls_verifier import (
    CpuBlsVerifier,
    MockBlsVerifier,
    ThreadBufferedVerifier,
)
from lodestar_tpu.chain.supervisor import (
    BREAKER_CLOSED,
    BREAKER_OPEN,
    DeviceDeadlineExceeded,
    SupervisedBlsVerifier,
)
from lodestar_tpu.observability.stages import PipelineMetrics
from lodestar_tpu.testing import faults

needs_native = pytest.mark.skipif(
    not native.HAVE_NATIVE_BLS, reason="native BLS tier unavailable"
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear(reset_counters=True)
    yield
    faults.clear(reset_counters=True)


def _sets(n, salt=0, bad=()):
    """n sets with distinct roots; indices in `bad` are mis-signed."""
    out = []
    for i in range(n):
        sk = bls.interop_secret_key(i + salt)
        msg = bytes([i & 0xFF, salt & 0xFF]) + b"\x33" * 30
        signer = bls.interop_secret_key(i + salt + 700) if i in bad else sk
        out.append(
            bls.SignatureSet(
                pubkey=sk.to_public_key(),
                message=msg,
                signature=signer.sign(msg).to_bytes(),
            )
        )
    return out


def _stub_kernels(verifier, verdict=True):
    """Constant-verdict device dispatches; marshalling still runs."""
    k = verifier.kernels
    ret = lambda *a, **kw: np.bool_(verdict)
    k.verify_batch = ret
    k.verify_batch_raw = ret
    k.verify_grouped = ret
    k.verify_grouped_raw = ret
    k.verify_pk_grouped = ret
    k.verify_pk_grouped_raw = ret
    k.verify_individual = lambda arrs, *a, **kw: np.full(
        arrs.valid.shape, verdict
    )

    def bisect_tree(arrs, r_bits):
        m = 1 << max(0, (arrs.valid.shape[0] - 1).bit_length())
        levels = []
        n = m
        while n >= 1:
            levels.append(np.zeros((n, 2, 3, 2, 32), np.int32))
            if n == 1:
                break
            n //= 2
        return np.bool_(verdict), levels

    k.verify_bisect_tree = bisect_tree
    k.probe_nodes = lambda fs: np.full((fs.shape[0],), verdict)


# --- scripted fakes for the breaker state machine ----------------------------


class ScriptedDevice:
    """Pops one behavior per dispatch: 'ok' | 'false' | 'raise' |
    ('hang', seconds). The last behavior repeats forever."""

    observer = None

    def __init__(self, *script):
        self.script = list(script) or ["ok"]
        self.calls = 0

    def _step(self):
        self.calls += 1
        b = self.script[0]
        if len(self.script) > 1:
            self.script.pop(0)
        if isinstance(b, tuple) and b[0] == "hang":
            time.sleep(b[1])
            return "ok"
        if b == "raise":
            raise RuntimeError("synthetic xla failure")
        return b

    def verify_signature_sets(self, sets):
        return self._step() == "ok"

    def verify_signature_sets_individual(self, sets):
        b = self._step()
        if b == "ok":
            return [True] * len(sets)
        return [False] * len(sets)


class CountingCpu(MockBlsVerifier):
    def __init__(self, result=True):
        super().__init__(result)
        self.calls = 0

    def verify_signature_sets(self, sets):
        self.calls += 1
        return super().verify_signature_sets(sets)

    def verify_signature_sets_individual(self, sets):
        self.calls += 1
        return super().verify_signature_sets_individual(sets)


def _sup(device, cpu=None, **kw):
    p = kw.pop("observer", None) or PipelineMetrics()
    kw.setdefault("deadline_s", 5.0)
    kw.setdefault("failure_threshold", 2)
    kw.setdefault("retries", 1)
    kw.setdefault("retry_base_delay_s", 0.001)
    kw.setdefault("canary_thread", False)
    kw.setdefault("canary_sets", [object()])
    return (
        SupervisedBlsVerifier(
            device, cpu if cpu is not None else CountingCpu(), observer=p, **kw
        ),
        p,
    )


# --- breaker state machine ---------------------------------------------------


def test_healthy_device_passthrough_no_cpu_work():
    dev = ScriptedDevice("ok")
    sup, p = _sup(dev)
    assert sup.verify_signature_sets([object(), object()])
    assert sup.verify_signature_sets_individual([object()]) == [True]
    assert sup.cpu.calls == 0  # the steady state pays zero oracle work
    assert sup.breaker_state == BREAKER_CLOSED
    snap = p.supervisor_snapshot()
    assert snap["degraded"] is False
    assert snap["fallbacks"] == {} and snap["retries"] == 0


def test_transient_error_retried_then_recovers():
    dev = ScriptedDevice("raise", "ok")  # first attempt fails, retry wins
    sup, p = _sup(dev)
    assert sup.verify_signature_sets([object()])
    assert dev.calls == 2
    assert sup.cpu.calls == 0  # retry succeeded: no fallback
    assert p.supervisor_retries.value() == 1
    assert sup.breaker_state == BREAKER_CLOSED


def test_persistent_error_falls_back_to_cpu_oracle():
    dev = ScriptedDevice("raise")
    sup, p = _sup(dev)
    assert sup.verify_signature_sets([object()]) is True  # CPU verdict
    assert dev.calls == 2  # attempt + one retry
    assert sup.cpu.calls == 1
    assert p.supervisor_fallbacks.value(reason="exception") == 1
    assert p.supervisor_retries.value() == 1


def test_breaker_opens_after_threshold_and_routes_straight_to_cpu():
    dev = ScriptedDevice("raise")
    sup, p = _sup(dev, failure_threshold=2)
    sup.verify_signature_sets([object()])
    assert sup.breaker_state == BREAKER_CLOSED
    sup.verify_signature_sets([object()])
    assert sup.breaker_state == BREAKER_OPEN
    assert p.supervisor_breaker_state.value() == 2
    assert p.supervisor_transitions.value(to="open") == 1
    calls_before = dev.calls
    assert sup.verify_signature_sets([object()]) is True
    assert dev.calls == calls_before  # device never touched while open
    assert p.supervisor_fallbacks.value(reason="breaker_open") == 1
    assert sup.verify_signature_sets_individual([object()]) == [True]
    assert p.supervisor_fallbacks.value(reason="breaker_open") == 2


def test_canary_recloses_breaker_and_failure_reopens():
    # each failed dispatch burns TWO script entries (attempt + retry)
    dev = ScriptedDevice(
        "raise", "raise", "raise", "raise", "false", "ok"
    )
    sup, p = _sup(dev, failure_threshold=2)
    sup.verify_signature_sets([object()])
    sup.verify_signature_sets([object()])
    assert sup.breaker_state == BREAKER_OPEN
    # first canary: device verdict False -> probe fails, breaker reopens
    assert sup.probe() is False
    assert sup.breaker_state == BREAKER_OPEN
    assert p.supervisor_canary.value(outcome="fail") == 1
    assert p.supervisor_transitions.value(to="half_open") == 1
    # second canary: device healthy again -> closed
    assert sup.probe() is True
    assert sup.breaker_state == BREAKER_CLOSED
    assert p.supervisor_canary.value(outcome="ok") == 1
    assert p.supervisor_transitions.value(to="closed") == 1
    # production traffic rides the device again
    calls_before = dev.calls
    assert sup.verify_signature_sets([object()])
    assert dev.calls == calls_before + 1


def test_background_canary_thread_recloses():
    dev = ScriptedDevice("raise", "raise", "raise", "raise", "ok")
    sup, p = _sup(
        dev, failure_threshold=2, canary_thread=True, cooldown_s=0.02
    )
    sup.verify_signature_sets([object()])
    sup.verify_signature_sets([object()])
    assert sup.breaker_state == BREAKER_OPEN
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and sup.breaker_state != BREAKER_CLOSED:
        time.sleep(0.01)
    assert sup.breaker_state == BREAKER_CLOSED
    assert p.supervisor_canary.value(outcome="ok") >= 1
    sup.close()


def test_deadline_blowout_abandons_worker_and_serves_cpu():
    dev = ScriptedDevice(("hang", 1.0), ("hang", 1.0), "ok")
    sup, p = _sup(dev, deadline_s=0.05, failure_threshold=10)
    t0 = time.monotonic()
    assert sup.verify_signature_sets([object()]) is True  # CPU verdict
    assert time.monotonic() - t0 < 0.8  # did NOT wait out the hang
    assert p.supervisor_deadline_exceeded.value() == 1
    assert p.supervisor_retries.value() == 0  # deadlines are not retried
    assert p.supervisor_fallbacks.value(reason="deadline") == 1
    assert sup.cpu.calls == 1
    # the wedged worker was abandoned: a fresh dispatch works (the second
    # hang is still draining on the abandoned thread)
    assert sup.verify_signature_sets([object()]) is True
    time.sleep(1.2)  # let abandoned workers drain before the next test
    sup.close()


def test_abandoned_worker_cap_bounds_thread_leak():
    """An infinitely-wedged device must not leak one thread per deadline:
    past MAX_ABANDONED wedged workers, dispatches fail fast on the same
    DeviceDeadlineExceeded path (CPU tier keeps serving) until a wedged
    call finally drains."""
    from lodestar_tpu.chain.supervisor import _DeadlineDispatcher

    release = threading.Event()
    d = _DeadlineDispatcher()
    started = []

    def wedge():
        started.append(1)
        release.wait(30.0)
        return True

    for _ in range(d.MAX_ABANDONED):
        with pytest.raises(DeviceDeadlineExceeded):
            d.run(wedge, 0.01)
    assert len(started) == d.MAX_ABANDONED
    # at the cap: fail-fast, no new worker spawned
    with pytest.raises(DeviceDeadlineExceeded, match="refusing to spawn"):
        d.run(wedge, 0.01)
    assert len(started) == d.MAX_ABANDONED
    # wedged calls drain -> capacity returns
    release.set()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        try:
            assert d.run(lambda: "ok", 1.0) == "ok"
            break
        except DeviceDeadlineExceeded:
            time.sleep(0.02)
    else:
        pytest.fail("dispatcher never recovered after workers drained")
    d.close()


def test_negative_verdict_audit_overturns_flaky_false():
    dev = ScriptedDevice("false")
    cpu = CountingCpu(True)  # the oracle says the sets are valid
    sup, p = _sup(dev, cpu, failure_threshold=3)
    assert sup.verify_signature_sets([object()]) is True  # oracle wins
    assert p.supervisor_verdict_mismatches.value() == 1
    assert p.supervisor_fallbacks.value(reason="negative_audit") == 1
    # mismatches are device failures: two more open the breaker
    sup.verify_signature_sets([object()])
    sup.verify_signature_sets([object()])
    assert sup.breaker_state == BREAKER_OPEN


def test_genuine_negative_confirmed_by_oracle_not_a_failure():
    dev = ScriptedDevice("false")
    cpu = CountingCpu(False)  # oracle agrees: invalid
    sup, p = _sup(dev, cpu)
    assert sup.verify_signature_sets([object()]) is False
    assert p.supervisor_verdict_mismatches.value() == 0
    assert sup.breaker_state == BREAKER_CLOSED  # agreement resets failures
    snap = p.supervisor_snapshot()
    assert snap["degraded"] is False  # auditing is the healthy path


def test_individual_audit_rechecks_only_rejected_sets():
    class HalfBad:
        observer = None

        def verify_signature_sets_individual(self, sets):
            return [i % 2 == 0 for i in range(len(sets))]

    audited = []

    class Oracle(CountingCpu):
        def verify_signature_sets_individual(self, sets):
            audited.append(len(sets))
            return [True] * len(sets)

    sup, p = _sup(HalfBad(), Oracle())
    out = sup.verify_signature_sets_individual([object()] * 4)
    assert out == [True, True, True, True]  # oracle overturned the odds
    assert audited == [2]  # ONLY the two rejected sets re-checked
    assert p.supervisor_verdict_mismatches.value() == 2


def test_both_tiers_failed_resolves_false_and_counts():
    class BrokenCpu:
        def verify_signature_sets(self, sets):
            raise RuntimeError("oracle down too")

        def verify_signature_sets_individual(self, sets):
            raise RuntimeError("oracle down too")

    dev = ScriptedDevice("raise")
    sup, p = _sup(dev, BrokenCpu())
    assert sup.verify_signature_sets([object()]) is False
    assert sup.verify_signature_sets_individual([object()] * 2) == [False] * 2
    assert p.supervisor_both_tiers_failed.value() == 2
    assert p.supervisor_snapshot()["degraded"] is True


def test_waiters_get_oracle_verdicts_through_thread_buffered_facade():
    """The acceptance wiring: ThreadBufferedVerifier._run_batch resolves
    waiters with CPU-oracle verdicts on device failure — blanket False
    only when both tiers fail."""
    dev = ScriptedDevice("raise")
    sup, p = _sup(dev)
    tbv = ThreadBufferedVerifier(sup, max_sigs=4, max_wait_ms=20)
    assert tbv.verify_signature_sets([object()], batchable=True) is True
    assert p.supervisor_fallbacks.value(reason="exception") >= 1

    class BrokenCpu:
        def verify_signature_sets(self, sets):
            raise RuntimeError("down")

        def verify_signature_sets_individual(self, sets):
            raise RuntimeError("down")

    sup2, p2 = _sup(ScriptedDevice("raise"), BrokenCpu())
    tbv2 = ThreadBufferedVerifier(sup2, max_sigs=4, max_wait_ms=20)
    assert tbv2.verify_signature_sets([object()], batchable=True) is False
    assert p2.supervisor_both_tiers_failed.value() >= 1


# --- waiter-timeout escape (satellite 1) -------------------------------------


def test_wedged_flush_thread_cannot_deadlock_waiters():
    release = threading.Event()
    first = [True]

    class WedgedVerifier:
        def verify_signature_sets(self, sets):
            if first[0]:
                first[0] = False
                release.wait(10.0)  # wedged far past every deadline
                return True
            return True

        def verify_signature_sets_individual(self, sets):
            return [True] * len(sets)

    p = PipelineMetrics()
    tbv = ThreadBufferedVerifier(
        WedgedVerifier(), max_sigs=8, max_wait_ms=10,
        pipeline=p, waiter_timeout_s=0.2,
    )
    t0 = time.monotonic()
    # the flush timer thread wedges inside the verifier; THIS caller must
    # escape at the waiter timeout instead of blocking forever
    assert tbv.verify_signature_sets([object()], batchable=True) is False
    assert 0.15 < time.monotonic() - t0 < 5.0
    assert p.waiter_timeouts.value() == 1
    release.set()
    # the facade stays usable afterwards
    assert tbv.verify_signature_sets([object()], batchable=True) is True


# --- fault injection at the TpuBlsVerifier seam ------------------------------


def _supervised_device_stack(verdict=True, **kw):
    """Real DeviceBlsVerifier (kernels stubbed) under the supervisor with
    the REAL CpuBlsVerifier oracle."""
    from lodestar_tpu.chain.bls_verifier import DeviceBlsVerifier

    p = PipelineMetrics()
    dev = DeviceBlsVerifier(observer=p)
    _stub_kernels(dev._inner, verdict=verdict)
    kw.setdefault("deadline_s", 5.0)
    kw.setdefault("failure_threshold", 3)
    kw.setdefault("retries", 1)
    kw.setdefault("retry_base_delay_s", 0.001)
    kw.setdefault("canary_thread", False)
    kw.setdefault("canary_sets", _sets(2, salt=900))
    sup = SupervisedBlsVerifier(dev, CpuBlsVerifier(), observer=p, **kw)
    return sup, p


@needs_native
def test_injected_exception_yields_oracle_verdicts():
    """ISSUE 4 acceptance: with exception faults at the device seam, no
    valid set is ever reported invalid — verdicts stay bit-identical to
    the CpuBlsVerifier oracle."""
    sup, p = _supervised_device_stack()
    sets = _sets(4, bad={2})
    oracle = CpuBlsVerifier().verify_signature_sets_individual(sets)
    assert oracle == [True, True, False, True]
    faults.configure("exception")
    assert sup.verify_signature_sets_individual(sets) == oracle
    assert sup.verify_signature_sets(_sets(3, salt=50)) is True
    assert p.supervisor_fallbacks.value(reason="exception") == 2
    assert faults.snapshot()["injected"]["exception"] >= 2
    # repeated failures open the breaker — observable on the state gauge
    sup.verify_signature_sets(_sets(2, salt=60))
    assert p.supervisor_breaker_state.value() == 2
    assert sup.verify_signature_sets_individual(sets) == oracle  # still right
    # faults cleared -> manual canary re-closes
    faults.clear()
    assert sup.probe() is True
    assert p.supervisor_breaker_state.value() == 0


@needs_native
def test_injected_flaky_verdicts_rescued_by_negative_audit():
    """flaky mode flips device verdicts True->False (the physical
    corruption direction); the negative-verdict audit must keep the
    reported verdicts bit-identical to the oracle."""
    sup, p = _supervised_device_stack()
    sets = _sets(4, salt=10, bad={1})
    oracle = CpuBlsVerifier().verify_signature_sets_individual(sets)
    faults.configure("flaky")  # rate 1.0: every True flips
    assert sup.verify_signature_sets_individual(sets) == oracle
    assert p.supervisor_verdict_mismatches.value() >= 1
    assert sup.verify_signature_sets(_sets(2, salt=70)) is True  # audit wins
    assert faults.snapshot()["injected"]["flaky"] >= 1


@needs_native
def test_injected_deadline_blowout_survives_flush_thread():
    """deadline mode wedges the dispatch past the supervisor deadline:
    waiters still get oracle verdicts through the facade, the deadline
    counter ticks, and the flush thread survives to serve the next
    (clean) batch."""
    sup, p = _supervised_device_stack(deadline_s=0.05, failure_threshold=10)
    tbv = ThreadBufferedVerifier(sup, max_sigs=4, max_wait_ms=10)
    sets = _sets(3, salt=20, bad={0})
    faults.configure("deadline:0.4")
    t0 = time.monotonic()
    # merged batch False (bad set) -> per-set fallback -> all through the
    # supervisor; every device attempt blows the deadline, oracle serves
    assert tbv.verify_signature_sets(sets, batchable=True) is False
    assert time.monotonic() - t0 < 5.0
    assert p.supervisor_deadline_exceeded.value() >= 1
    assert p.supervisor_fallbacks.value(reason="deadline") >= 1
    good = _sets(2, salt=30)
    assert tbv.verify_signature_sets(good, batchable=True) is True
    faults.clear()
    time.sleep(0.5)  # drain abandoned workers
    assert tbv.verify_signature_sets(good, batchable=True) is True
    sup.close()


@needs_native
def test_no_faults_device_path_untouched():
    """With faults off, the supervised path is a passthrough: device
    verdicts, zero fallbacks, zero retries, not degraded."""
    sup, p = _supervised_device_stack()
    assert sup.verify_signature_sets(_sets(3)) is True
    assert sup.verify_signature_sets_individual(_sets(3)) == [True] * 3
    snap = p.supervisor_snapshot()
    assert snap["fallbacks"] == {}
    assert snap["retries"] == 0 and snap["deadline_exceeded"] == 0
    assert snap["degraded"] is False


def test_fault_spec_parsing_and_unknown_mode():
    doc = faults.configure("exception:0.5,latency:0.01")
    assert doc["active"] and doc["modes"] == {
        "exception": 0.5, "latency": 0.01,
    }
    faults.clear()
    assert not faults.active()
    with pytest.raises(ValueError, match="unknown fault mode"):
        faults.configure("segfault")


def test_fault_spec_malformed_param_is_a_clean_error():
    """`chip:abc` must raise a ValueError naming the mode and parameter —
    not a bare float() traceback — so the /debug/faults 400 body (and a
    drill operator's terminal) says what to fix."""
    with pytest.raises(ValueError, match="'chip'.*'abc' is not a number"):
        faults.configure("chip:abc")
    with pytest.raises(ValueError, match="'latency'.*not a number"):
        faults.configure("latency:fast")
    assert not faults.active()  # a rejected spec must not half-arm


def test_fault_spec_rejects_negative_and_fractional_chip():
    with pytest.raises(ValueError, match="must be >= 0"):
        faults.configure("latency:-1")
    with pytest.raises(ValueError, match="integer chip index"):
        faults.configure("chip:1.5")
    assert not faults.active()


def test_fault_spec_combined_modes_and_blank_parts():
    """Every mode in one spec, defaults applied when `:param` is omitted,
    stray commas/whitespace tolerated."""
    doc = faults.configure(" exception , latency:0.2 ,, chip:1 , flaky ")
    try:
        assert doc["modes"] == {
            "exception": 1.0,  # default probability
            "latency": 0.2,
            "chip": 1.0,
            "flaky": 1.0,      # default probability
        }
    finally:
        faults.clear(reset_counters=True)


def test_clear_keeps_counters_unless_reset_requested():
    """A bare clear() disarms but keeps injection counters (a degraded
    bench round stays self-labelled); reset_counters=True zeroes them."""
    faults.configure("exception")
    with pytest.raises(faults.InjectedFault):
        faults.on_device_dispatch(1)
    faults.clear()
    assert not faults.active()
    assert faults.snapshot()["injected"]["exception"] >= 1
    faults.clear(reset_counters=True)
    assert faults.snapshot()["injected"] == {}


# --- /debug/breaker and /debug/faults ----------------------------------------


def test_debug_breaker_and_faults_endpoints():
    from lodestar_tpu.metrics import MetricsRegistry, MetricsServer

    dev = ScriptedDevice("raise")
    sup, p = _sup(dev, failure_threshold=1)
    server = MetricsServer(
        MetricsRegistry(), port=0, breaker=sup.breaker_snapshot
    )
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{url}/debug/breaker") as r:
            doc = json.load(r)
        assert doc["wired"] and doc["state"] == "closed"
        assert doc["counters"]["degraded"] is False
        # one failure trips the threshold-1 breaker: observable live
        sup.verify_signature_sets([object()])
        with urllib.request.urlopen(f"{url}/debug/breaker") as r:
            doc = json.load(r)
        assert doc["state"] == "open" and doc["state_value"] == 2
        assert doc["counters"]["degraded"] is True
        assert "open_for_s" in doc

        # faults control surface: arm, inspect, reject junk, clear
        req = urllib.request.Request(
            f"{url}/debug/faults?set=latency:0.01,flaky:0.5", method="POST"
        )
        with urllib.request.urlopen(req) as r:
            doc = json.load(r)
        assert doc["modes"] == {"latency": 0.01, "flaky": 0.5}
        assert faults.active()
        with urllib.request.urlopen(f"{url}/debug/faults") as r:
            assert json.load(r)["active"] is True
        try:
            urllib.request.urlopen(f"{url}/debug/faults?set=bogus")
            assert False, "expected 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
        with urllib.request.urlopen(f"{url}/debug/faults?clear=1") as r:
            assert json.load(r)["active"] is False
        assert not faults.active()

        # counters survive a bare clear (degraded runs stay labelled);
        # ?clear=1&reset_counters=1 is the drill-teardown full reset
        faults.configure("exception")
        with pytest.raises(faults.InjectedFault):
            faults.on_device_dispatch(1)
        with urllib.request.urlopen(f"{url}/debug/faults?clear=1") as r:
            doc = json.load(r)
        assert doc["active"] is False and doc["injected"]["exception"] >= 1
        req = urllib.request.Request(
            f"{url}/debug/faults?clear=1&reset_counters=1", method="POST"
        )
        with urllib.request.urlopen(req) as r:
            doc = json.load(r)
        assert doc["active"] is False and doc["injected"] == {}
    finally:
        server.close()


def test_debug_breaker_unwired_reports_not_wired():
    from lodestar_tpu.metrics import MetricsRegistry, MetricsServer

    server = MetricsServer(MetricsRegistry(), port=0)
    server.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug/breaker"
        ) as r:
            assert json.load(r) == {"wired": False}
    finally:
        server.close()


# --- fault-injected gossip -> import (e2e wiring) ----------------------------


@pytest.fixture()
def supervised_chain():
    """A chain whose verifier is the FULL production stack —
    ThreadBufferedVerifier over SupervisedBlsVerifier over a (stubbed)
    DeviceBlsVerifier — with a constant-True oracle standing in for the
    CPU tier (the real-oracle verdict match is covered by the direct
    tests above; gossip blocks here carry interop placeholder sigs)."""
    from lodestar_tpu.chain import BeaconChain
    from lodestar_tpu.chain.bls_verifier import DeviceBlsVerifier
    from lodestar_tpu.config.beacon_config import BeaconConfig, ChainForkConfig
    from lodestar_tpu.config.chain_config import MINIMAL_CHAIN_CONFIG
    from lodestar_tpu.metrics import create_beacon_metrics
    from lodestar_tpu.params.presets import MINIMAL
    from lodestar_tpu.state_transition import interop_genesis_state
    from lodestar_tpu.types import get_types

    types = get_types(MINIMAL).phase0
    fork_config = ChainForkConfig(MINIMAL_CHAIN_CONFIG, MINIMAL)
    state = interop_genesis_state(
        fork_config, types, 16, genesis_time=1_600_000_000
    )
    config = BeaconConfig(
        MINIMAL_CHAIN_CONFIG, bytes(state.genesis_validators_root), MINIMAL
    )
    metrics = create_beacon_metrics()
    dev = DeviceBlsVerifier(observer=metrics.pipeline)
    _stub_kernels(dev._inner)
    sup = SupervisedBlsVerifier(
        dev, CountingCpu(True), observer=metrics.pipeline,
        deadline_s=5.0, failure_threshold=3, retries=1,
        retry_base_delay_s=0.001, canary_thread=False,
        canary_sets=[object()],
    )
    verifier = ThreadBufferedVerifier(sup, prom=metrics, max_wait_ms=10)
    chain = BeaconChain(config, types, state, verifier=verifier)
    chain.metrics = metrics
    chain.clock.set_slot(1)
    return config, types, chain, sup, metrics


def test_gossip_import_survives_device_faults(supervised_chain):
    """ISSUE 4 acceptance wiring: with exception faults armed at the
    device seam, a gossip block still validates and imports (verdicts
    served by the oracle tier), the fallback counters tick, and the
    breaker state is observable — nothing resolves blanket-False."""
    import asyncio

    from lodestar_tpu.network.gossip.encoding import encode_message
    from lodestar_tpu.network.gossip.gossipsub import ValidationResult
    from lodestar_tpu.network.gossip.handlers import GossipHandlers
    from lodestar_tpu.network.gossip.topic import GossipTopic, GossipType

    config, types, chain, sup, metrics = supervised_chain
    block = chain.produce_block(1, randao_reveal=b"\x00" * 96)
    signed = types.SignedBeaconBlock(message=block, signature=b"\x11" * 96)
    wire = encode_message(signed.serialize())
    topic = GossipTopic(GossipType.beacon_block, b"\x01\x02\x03\x04")

    faults.configure("exception")
    handlers = GossipHandlers(config, types, chain)
    result = asyncio.run(handlers._process((topic, wire)))
    assert result is ValidationResult.ACCEPT
    assert bytes(chain.head_state.state.latest_block_header.state_root) != b""
    p = metrics.pipeline
    assert (
        p.supervisor_fallbacks.value(reason="exception")
        + p.supervisor_fallbacks.value(reason="breaker_open")
    ) >= 1
    assert p.supervisor_both_tiers_failed.value() == 0
    # the oracle tier did the serving
    assert sup.cpu.calls >= 1
    faults.clear()
