"""Beacon CLI with live networking: two real `lodestar-tpu beacon`
processes find each other via a bootstrap record and peer up.

Reference analog: two `lodestar beacon` processes with --bootnodes
(cli e2e; ENR file persistence from `cli/src/cmds/beacon`).
"""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


GENESIS_TIME = int(time.time())  # near-genesis clock: nodes are not syncing


def _spawn_beacon(extra, datadir):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO
    cmd = [
        sys.executable, "-m", "lodestar_tpu.cli", "beacon",
        "--genesis-validators", "8",
        "--genesis-time", str(GENESIS_TIME),
        "--datadir", datadir,
        "--run-seconds", "120",
        "--rest",
    ] + extra
    return subprocess.Popen(
        cmd, env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def _rest_json(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=2) as r:
        return json.loads(r.read())["data"]


@pytest.mark.slow
def test_two_cli_nodes_peer_up(tmp_path):
    da, db = str(tmp_path / "a"), str(tmp_path / "b")
    os.makedirs(da), os.makedirs(db)
    pa, pb = _free_port(), _free_port()
    ra, rb = _free_port(), _free_port()

    a = _spawn_beacon(["--port", str(pa), "--rest-port", str(ra)], da)
    try:
        # wait for node A's ENR file
        enr_path = os.path.join(da, "enr.txt")
        for _ in range(120):
            if os.path.exists(enr_path):
                break
            assert a.poll() is None, a.stdout.read().decode()[-2000:]
            time.sleep(1)
        else:
            raise AssertionError("node A never wrote its ENR")
        enr_text = open(enr_path).read().strip()
        assert enr_text.startswith("enr-tpu:")

        b = _spawn_beacon(
            ["--port", str(pb), "--rest-port", str(rb), "--bootnodes", enr_text],
            db,
        )
        try:
            # poll both REST endpoints until each sees the other as a peer
            deadline = time.time() + 60
            ok = False
            while time.time() < deadline:
                try:
                    peers_a = _rest_json(ra, "/eth/v1/node/peers")
                    peers_b = _rest_json(rb, "/eth/v1/node/peers")
                    ident_a = _rest_json(ra, "/eth/v1/node/identity")
                    if (
                        any(p["state"] == "connected" for p in peers_a)
                        and any(
                            p["peer_id"] == ident_a["peer_id"]
                            and p["state"] == "connected"
                            for p in peers_b
                        )
                    ):
                        ok = True
                        break
                except Exception:
                    pass
                assert a.poll() is None and b.poll() is None
                time.sleep(1)
            assert ok, "nodes never peered"
            # identity route serves a valid shareable record
            ident_b = _rest_json(rb, "/eth/v1/node/identity")
            assert ident_b["enr"].startswith("enr-tpu:")
        finally:
            b.terminate()
            b.wait(timeout=15)
    finally:
        a.terminate()
        a.wait(timeout=15)


def test_peerstore_persists_across_restart(tmp_path):
    """Known peers are saved to the datadir and restored into the routing
    table on restart (reference peer datastore persistence)."""
    import asyncio

    pytest.importorskip("cryptography")  # discovery identities need it

    from lodestar_tpu.cli.beacon import _load_peerstore, _save_peerstore
    from lodestar_tpu.network.discovery import ENR, Discovery
    from lodestar_tpu.network.transport import NodeIdentity

    class FakeNet:
        def __init__(self, discovery):
            self.discovery = discovery

    async def main():
        me = NodeIdentity.from_seed(b"store-me")
        other = NodeIdentity.from_seed(b"store-other")
        d = Discovery(
            me,
            ENR(node_id=me.peer_id, pubkey=me.public_bytes,
                ip="127.0.0.1", tcp_port=9000, udp_port=9001),
        )
        other_enr = ENR(
            node_id=other.peer_id, pubkey=other.public_bytes,
            ip="127.0.0.1", tcp_port=9002, udp_port=9003,
        ).sign(other)
        assert d.table.update(other_enr)
        _save_peerstore(str(tmp_path), FakeNet(d))

        # fresh process: empty table, restore from disk
        d2 = Discovery(
            me,
            ENR(node_id=me.peer_id, pubkey=me.public_bytes,
                ip="127.0.0.1", tcp_port=9000, udp_port=9001),
        )
        assert len(d2.table) == 0
        _load_peerstore(str(tmp_path), FakeNet(d2))
        restored = {e.node_id for e in d2.table.all()}
        assert other.peer_id in restored

    asyncio.run(main())
