"""Compile-ledger & cold-start observability (ISSUE 11).

The compile tax killed two driver rounds while being invisible; these
tests pin the accounting layer that makes it measurable: the wrap seam
records one event per (kernel, signature) with a persistent-cache
verdict, events fan out to every live PipelineMetrics and to
`/debug/compiles`, the startup timeline feeds the serving-ready SLO
gauge, the flight recorder survives a watchdog rc=124 as a post-mortem
inside the emitted JSON, and tools/bench_compare.py reports (but never
gates) the per-round compile-seconds delta.
"""

import importlib.util
import json
import os
import subprocess
import sys
import urllib.request

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from lodestar_tpu.observability.compile_ledger import (  # noqa: E402
    CompileLedger,
    StartupTimeline,
    ledger,
)
from lodestar_tpu.observability.flight_recorder import (  # noqa: E402
    FlightRecorder,
    recorder,
)
from lodestar_tpu.observability.stages import PipelineMetrics  # noqa: E402


def _load_tool(name):
    path = os.path.join(REPO_ROOT, "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- the wrap seam ----------------------------------------------------------


def test_wrap_records_one_event_per_kernel_signature():
    """First call per (kernel, signature) is a compile event; repeat
    calls with the same shape record nothing, a NEW shape records a
    second event."""
    import jax
    import jax.numpy as jnp

    led = CompileLedger()
    p = PipelineMetrics()
    led.attach(p)
    fn = led.wrap(jax.jit(lambda x: x + 1), "t_dedup_kernel")
    assert fn.__compile_ledger_kernel__ == "t_dedup_kernel"

    fn(jnp.arange(4.0))
    fn(jnp.arange(4.0))  # same signature: no second event
    snap = led.snapshot()
    assert snap["event_count"] == 1
    (event,) = snap["events"]
    assert event["kernel"] == "t_dedup_kernel"
    assert event["key"] == "float32[4]"
    assert event["seconds"] >= 0.0
    assert event["cache"] in ("off", "hit", "miss")
    assert snap["cumulative_seconds"] >= event["seconds"]

    fn(jnp.arange(8.0))  # new shape: new trace+compile, new event
    snap = led.snapshot()
    assert snap["event_count"] == 2
    assert snap["events"][1]["key"] == "float32[8]"

    # fan-out ticked the attached pipeline's families
    text = p.registry.expose()
    assert "lodestar_tpu_compile_events_total" in text
    assert 't_dedup_kernel' in text
    assert "lodestar_tpu_compile_cumulative_seconds" in text


def test_wrap_records_via_metrics_route_and_artifact(tmp_path):
    """Acceptance: a small jit driven through the PROCESS ledger seam
    shows up in (a) a live pipeline's /metrics exposition, (b) the
    /debug/compiles endpoint, (c) the compile_ledger.json artifact."""
    import jax
    import jax.numpy as jnp

    from lodestar_tpu.metrics.registry import MetricsRegistry
    from lodestar_tpu.metrics.server import MetricsServer

    p = PipelineMetrics()  # attaches itself to the global ledger
    fn = ledger().wrap(jax.jit(lambda x: x * 3), "t_route_kernel")
    fn(jnp.arange(6.0))

    text = p.registry.expose()
    assert "t_route_kernel" in text

    server = MetricsServer(MetricsRegistry())
    server.start()
    try:
        doc = json.load(
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/compiles"
            )
        )
    finally:
        server.close()
    assert {"ledger", "startup", "flight_recorder"} <= set(doc)
    kernels = [e["kernel"] for e in doc["ledger"]["events"]]
    assert "t_route_kernel" in kernels
    assert doc["flight_recorder"]["capacity"] >= 1

    path = ledger().write_artifact(str(tmp_path / "compile_ledger.json"))
    saved = json.load(open(path))
    assert "t_route_kernel" in [e["kernel"] for e in saved["events"]]
    assert "cache" in saved and "cumulative_seconds" in saved


def test_static_key_records_distinct_events_per_key():
    """The mesh seam's static_key (shape@chips) must create a NEW event
    after re-wrap with a different key — the post-eviction recompile."""
    led = CompileLedger()
    calls = []
    fn_a = led.wrap(lambda: calls.append("a"), "t_mesh_kernel",
                    static_key="(64, 64)@chips0,1")
    fn_b = led.wrap(lambda: calls.append("b"), "t_mesh_kernel",
                    static_key="(64, 64)@chips0,2")
    fn_a(), fn_a(), fn_b()
    snap = led.snapshot()
    assert snap["event_count"] == 2
    assert {e["key"] for e in snap["events"]} == {
        "(64, 64)@chips0,1", "(64, 64)@chips0,2"
    }
    assert calls == ["a", "a", "b"]


def test_cache_hit_miss_classification(tmp_path):
    """Against a fresh persistent-cache dir (threshold 0 so even tiny
    kernels persist): first compile = miss (new entry appears), an
    identical fresh jit = hit (loaded from the persistent cache, no new
    entry)."""
    import jax
    import jax.numpy as jnp

    try:
        from jax._src.compilation_cache import reset_cache
    except ImportError:
        pytest.skip("jax compilation-cache reset hook unavailable")

    prev_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    prev_min = getattr(
        jax.config, "jax_persistent_cache_min_compile_time_secs", 1.0
    )
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # the cache module latches its directory at first use; earlier
    # compiles in this process initialized it with the ambient config
    reset_cache()
    try:
        led = CompileLedger()
        x = jnp.arange(16.0)  # build inputs BEFORE the baseline listing
        first = led.wrap(jax.jit(lambda v: v * 2 + 1), "t_cache_first")
        first(x)
        # a NEW jit object of the same computation recompiles in-process
        # but loads from the persistent cache: no new entry => hit
        second = led.wrap(jax.jit(lambda v: v * 2 + 1), "t_cache_second")
        second(x)
        snap = led.snapshot()
        by_kernel = {e["kernel"]: e for e in snap["events"]}
        assert by_kernel["t_cache_first"]["cache"] == "miss"
        assert by_kernel["t_cache_second"]["cache"] == "hit"
        assert snap["cache"]["misses"] == 1
        assert snap["cache"]["hits"] == 1
        assert snap["cache"]["dir"] == str(tmp_path)
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min
        )
        reset_cache()


def test_batch_verifier_kernels_are_ledger_wrapped():
    """The production jit seam: every BatchVerifier kernel callable
    carries the ledger wrap (construction-time, before any dispatch)."""
    from lodestar_tpu.parallel.verifier import BatchVerifier

    bv = BatchVerifier(buckets=(4,))
    for attr, kernel in (
        ("_batch", "batch"),
        ("_individual", "individual"),
        ("_grouped", "grouped"),
        ("_pk_grouped", "pk_grouped"),
        ("_bisect_tree", "bisect_tree"),
        ("_bisect_probe", "bisect_probe"),
        # ISSUE 14: the standalone batched final exp and the Pallas
        # Miller tower ride the same seam
        ("_final_exp_batch", "final_exp_batch"),
        ("_miller_pallas", "miller_pallas"),
        # ISSUE 15: the zero-copy wire→device kernels (on-chip signature
        # decode) are the DEFAULT serving path — their compiles must be
        # first-class ledger events too
        ("_batch_raw", "batch_raw"),
        ("_grouped_raw", "grouped_raw"),
        ("_pk_grouped_raw", "pk_grouped_raw"),
        # ISSUE 18: the fused full-pairing Pallas kernel
        ("_pairing_pallas", "pairing_pallas"),
    ):
        assert getattr(bv, attr).__compile_ledger_kernel__ == kernel


def test_mesh_raw_twin_submit_is_ledger_wrapped():
    """ISSUE 15: the mesh dispatcher's raw-twin verifiers ride the same
    `_ledger_wrap_submit` seam as the limb twins — each (kind, shape,
    chips) raw verifier is one shard_map compile, recorded under the
    `sharded_grouped_raw` / `sharded_pk_grouped_raw` kernel names."""
    from lodestar_tpu.parallel.mesh import _ledger_wrap_submit

    class _V:
        def submit(self, *a):
            return True

    for kind in ("grouped_raw", "pk_grouped_raw"):
        v = _V()
        _ledger_wrap_submit(v, kind, (16, 8), (0, 1))
        assert v.submit.__compile_ledger_kernel__ == f"sharded_{kind}"


def test_fleet_twin_submit_is_ledger_wrapped_with_host_key():
    """ISSUE 20: two-level fleet verifiers record under their OWN kernel
    names (``fleet_<kind>``) with the host count in the static key — the
    same (kind, shape, chips) over 1 host vs 2 hosts is a different
    executable and the AOT store must not conflate them."""
    from lodestar_tpu.observability.compile_ledger import ledger
    from lodestar_tpu.parallel.mesh import _ledger_wrap_submit

    class _V:
        def submit(self, *a):
            return True

    for kind in ("grouped", "grouped_raw", "pk_grouped",
                 "pk_grouped_raw", "bisect"):
        v = _V()
        _ledger_wrap_submit(v, kind, (16, 8), (0, 1, 2, 3), hosts=2)
        assert v.submit.__compile_ledger_kernel__ == f"fleet_{kind}"
        assert v.submit() is True
    events = [e for e in ledger().snapshot()["events"]
              if e["kernel"].startswith("fleet_")]
    assert {e["kernel"] for e in events} >= {
        "fleet_grouped", "fleet_bisect"
    }
    for e in events:
        assert "@hosts2" in e["key"]


# -- flight recorder --------------------------------------------------------


def test_flight_recorder_ring_bounds_and_reports_drops():
    fr = FlightRecorder(capacity=4)
    for i in range(10):
        fr.record("t_kind", i=i)
    dump = fr.dump()
    assert dump["capacity"] == 4
    assert dump["recorded_total"] == 10
    assert dump["dropped"] == 6
    assert [e["i"] for e in dump["events"]] == [6, 7, 8, 9]
    assert all(e["kind"] == "t_kind" for e in dump["events"])
    assert dump["events"][-1]["seq"] == 10
    limited = fr.dump(limit=2)
    assert [e["i"] for e in limited["events"]] == [8, 9]
    assert limited["dropped"] == 8


def test_flight_recorder_singleton_records_compile_events():
    """The ledger's wrap seam drops compile_start/compile_end into the
    process ring — the started-but-unfinished signature a watchdog
    post-mortem looks for."""
    led = CompileLedger()
    fn = led.wrap(lambda: None, "t_flight_kernel", static_key="k")
    fn()
    kinds = [
        (e["kind"], e.get("kernel"))
        for e in recorder().dump()["events"]
        if e.get("kernel") == "t_flight_kernel"
    ]
    assert ("compile_start", "t_flight_kernel") in kinds
    assert ("compile_end", "t_flight_kernel") in kinds


# -- startup timeline / serving-ready SLO -----------------------------------


def test_startup_timeline_marks_and_serving_ready_gauge():
    p = PipelineMetrics()  # attaches to the global ledger for fan-out
    tl = StartupTimeline()
    t1 = tl.mark("t_phase_devices")
    ready = tl.mark_serving_ready()
    assert ready >= t1 >= 0.0
    snap = tl.snapshot()
    assert snap["serving_ready_s"] == pytest.approx(ready, abs=0.01)
    phases = [m["phase"] for m in snap["marks"]]
    assert phases == ["t_phase_devices", "serving_ready"]
    text = p.registry.expose()
    assert "lodestar_tpu_serving_ready_seconds" in text
    assert 't_phase_devices' in text  # startup_phase_seconds label


def test_process_start_anchor_predates_module_import():
    """Marks measure from PROCESS start (/proc/self/stat), so the first
    mark already includes interpreter+import time — it must be visibly
    nonzero, not a fresh monotonic zero."""
    tl = StartupTimeline()
    assert tl.mark("t_anchor_check") > 0.01


# -- cache prune observability ----------------------------------------------


def test_note_prune_ticks_gauges_and_lands_in_snapshot():
    led = CompileLedger()
    p = PipelineMetrics()
    led.attach(p)
    led.note_prune({
        "entries": 10,
        "entries_remaining": 7,
        "removed": ["a", "b", "c"],
        "removed_bytes": 3 << 20,
        "total_bytes": 7 << 20,
    })
    snap = led.snapshot()
    assert snap["last_prune"]["entries_remaining"] == 7
    assert snap["last_prune"]["removed"] == 3
    assert snap["last_prune"]["removed_bytes"] == 3 << 20
    text = p.registry.expose()
    assert "lodestar_tpu_compile_cache_pruned_bytes_total" in text
    assert "lodestar_tpu_compile_cache_entries 7" in text


def test_prune_tool_emits_structured_log_and_remaining_count(
    tmp_path, capsys
):
    prune_mod = _load_tool("prune_compile_cache")
    for i in range(4):
        (tmp_path / f"entry{i}").write_bytes(b"x" * 1024)
    result = prune_mod.prune(str(tmp_path), limit_gb=2048 / (1 << 30))
    assert result["entries"] == 4
    assert result["entries_remaining"] == 4 - len(result["removed"])
    assert len(result["removed"]) == 2
    err = capsys.readouterr().err
    lines = [
        json.loads(line) for line in err.splitlines()
        if line.startswith("{")
    ]
    assert any(
        rec.get("event") == "compile_cache_prune"
        and rec["entries_remaining"] == 2
        for rec in lines
    )


def test_prune_dry_run_is_silent_and_destroys_nothing(tmp_path, capsys):
    prune_mod = _load_tool("prune_compile_cache")
    (tmp_path / "keep").write_bytes(b"x" * 4096)
    result = prune_mod.prune(str(tmp_path), limit_gb=1024 / (1 << 30),
                             dry_run=True)
    assert result["removed"] and (tmp_path / "keep").exists()
    assert "compile_cache_prune" not in capsys.readouterr().err


# -- watchdog post-mortem (end to end) --------------------------------------


def test_watchdog_rc124_leaves_flight_recorder_post_mortem(tmp_path):
    """End to end: a bench whose main thread wedges past the global
    deadline exits rc=124 but its final JSON is parseable and carries
    `timed_out`, `watchdog_fired_after_s`, and the flight-recorder dump
    naming the wedged phase; tools/bench_compare.py then SKIPS the round
    with a printed note instead of gating its partial rates."""
    code = (
        "import sys, time\n"
        f"sys.path.insert(0, {REPO_ROOT!r})\n"
        "from lodestar_tpu.observability.bench_emit import BenchEmitter\n"
        "from lodestar_tpu.observability import flight_recorder\n"
        "flight_recorder.record('dispatch', path='grouped', sets=64)\n"
        "em = BenchEmitter('m', 'sets/s', global_deadline_s=0.3)\n"
        "with em.phase('wedged_compile'):\n"
        "    time.sleep(30)\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    out, _ = proc.communicate(timeout=20)
    assert proc.returncode == 124
    doc = json.loads(out.strip().splitlines()[-1])
    assert doc["timed_out"] is True
    assert doc["watchdog_fired_after_s"] == 0.3
    assert doc["phases"]["wedged_compile"]["status"] == "killed"
    kinds = [e["kind"] for e in doc["flight_recorder"]["events"]]
    assert "dispatch" in kinds  # pre-wedge activity survived
    assert "watchdog_fired" in kinds
    phase_events = [
        e for e in doc["flight_recorder"]["events"]
        if e["kind"] == "bench_phase"
    ]
    assert phase_events and phase_events[0]["phase"] == "wedged_compile"

    # the timed-out round is skip-but-logged by the regression gate
    bench_compare = _load_tool("bench_compare")
    good = {
        "metric": "m", "value": 100.0, "unit": "sets/s",
        "phases": {"p": {"status": "ok",
                         "rows": {"device_sets_per_sec": 100.0}}},
    }
    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": good}))
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"parsed": good}))
    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps({"rc": 124, "parsed": doc}))
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench_compare.main(["--dir", str(tmp_path)])
    report = buf.getvalue()
    assert rc == 0
    assert "skipping r03" in report and "timed out mid-run" in report
    assert "r01 -> r02" in report  # gate ran on the completed rounds


# -- build info / runtime identity ------------------------------------------


def test_runtime_info_shape_and_build_info_gauge():
    from lodestar_tpu.utils.jax_env import runtime_info

    info = runtime_info(enumerate_devices=False)
    assert set(info) == {
        "jax", "jaxlib", "backend", "device_kind", "device_count",
        "mesh_divisor", "compile_cache",
    }
    assert all(isinstance(v, str) for v in info.values())
    assert info["jax"] not in ("", "none")  # jax is importable here
    # device-free variant never initializes a backend: count stays 0
    assert info["device_count"] == "0"

    p = PipelineMetrics()
    p.set_build_info(info)
    text = p.registry.expose()
    assert "lodestar_tpu_build_info" in text
    assert f'jax="{info["jax"]}"' in text


def test_build_info_tolerates_missing_keys():
    p = PipelineMetrics()
    p.set_build_info({"jax": "0.0"})  # everything else -> "unknown"
    text = p.registry.expose()
    assert 'backend="unknown"' in text


# -- bench_compare compile-seconds delta ------------------------------------


def test_bench_compare_prints_compile_delta_without_gating(tmp_path):
    bench_compare = _load_tool("bench_compare")

    def _doc(rate, compile_s):
        return {
            "metric": "m", "value": rate, "unit": "sets/s",
            "phases": {"p": {"status": "ok",
                             "rows": {"device_sets_per_sec": rate}}},
            "compile_ledger": {"cumulative_seconds": compile_s},
        }

    (tmp_path / "BENCH_r01.json").write_text(
        json.dumps({"parsed": _doc(100.0, 12.5)}))
    # compile seconds grew 40x — informational only, NEVER a regression
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"parsed": _doc(100.0, 500.0)}))
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench_compare.main(["--dir", str(tmp_path)])
    report = buf.getvalue()
    assert rc == 0
    assert "cumulative compile seconds 12.5s -> 500.0s" in report
    assert "not gated" in report
    assert "OK: no gated key regressed" in report
