"""Discovery: ENR signing/encoding, routing table, live UDP lookups.

Reference analog: discv5 usage in `network/peers/discover.ts` — bootstrap
from bootnodes, iterative lookups populate the table, subnet-targeted
queries filter by attnets bits.
"""

import asyncio

import pytest

# the node identity layer (ENR signing, noise handshake) needs the
# `cryptography` wheel, which minimal CI images may lack — skip, not error
pytest.importorskip("cryptography")

from lodestar_tpu.network.discovery import (
    ENR,
    Discovery,
    RoutingTable,
    _distance,
)
from lodestar_tpu.network.transport import NodeIdentity


def run(coro):
    return asyncio.run(asyncio.wait_for(coro, 60.0))


def _identity(i: int) -> NodeIdentity:
    return NodeIdentity.from_seed(bytes([i]) * 4)


def _enr(identity: NodeIdentity, udp_port: int = 0, attnets: int = 0) -> ENR:
    return ENR(
        node_id=identity.peer_id,
        pubkey=identity.public_bytes,
        ip="127.0.0.1",
        tcp_port=9000,
        udp_port=udp_port,
        attnets=attnets,
    ).sign(identity)


def test_enr_sign_verify_roundtrip():
    ident = _identity(1)
    enr = _enr(ident, udp_port=1234, attnets=0b1010)
    assert enr.verify()
    decoded, _ = ENR.decode(enr.encode())
    assert decoded.verify()
    assert decoded.node_id == ident.peer_id
    assert decoded.udp_port == 1234
    assert decoded.has_attnet(1) and decoded.has_attnet(3)
    assert not decoded.has_attnet(0)
    # tampering breaks the signature
    tampered = _enr(ident)
    tampered.attnets = 0xFF
    assert not tampered.verify()


def test_enr_rejects_wrong_identity():
    enr = _enr(_identity(1))
    enr.node_id = _identity(2).peer_id  # claim someone else's id
    assert not enr.verify()


def test_routing_table_buckets_and_closest():
    local = _identity(0)
    table = RoutingTable(local.peer_id)
    enrs = [_enr(_identity(i)) for i in range(1, 40)]
    kept = [enr for enr in enrs if table.update(enr)]
    # most random ids share the top log2-distance buckets, which cap at
    # K_BUCKET_SIZE — the table is bounded, not exhaustive
    assert len(table) == len(kept) <= 39
    assert len(kept) >= 16
    target = _identity(99).peer_id
    closest = table.closest(target, 5)
    dists = [_distance(target, e.node_id) for e in closest]
    assert dists == sorted(dists)
    kept_dists = sorted(_distance(target, e.node_id) for e in kept)
    assert dists == kept_dists[:5]


def test_table_ignores_invalid_and_self():
    local = _identity(0)
    table = RoutingTable(local.peer_id)
    assert not table.update(_enr(local))  # self
    bad = _enr(_identity(1))
    bad.signature = b"\x00" * 64
    assert not table.update(bad)


def test_live_ping_and_lookup_converges():
    async def main():
        idents = [_identity(10 + i) for i in range(5)]
        discos = []
        for ident in idents:
            d = Discovery(ident, _enr(ident))
            await d.start()
            discos.append(d)
        try:
            # everyone bootstraps off node 0
            boot = discos[0].local_enr
            for d in discos[1:]:
                await d.bootstrap([boot])
            # node 0 has learned the others from their pings; lookups spread
            for d in discos[1:]:
                await d.lookup(d.local_enr.node_id)
            # every node should now know every other node
            for d in discos:
                known = {e.node_id for e in d.table.all()}
                expected = {x.local_enr.node_id for x in discos} - {d.local_enr.node_id}
                assert expected <= known, (
                    f"{d.local_enr.node_id[:8]} missing {len(expected - known)}"
                )
        finally:
            for d in discos:
                d.stop()

    run(main())


def test_subnet_targeted_query_and_attnets_update():
    async def main():
        a, b, c = (_identity(20 + i) for i in range(3))
        da = Discovery(a, _enr(a))
        db = Discovery(b, _enr(b, attnets=1 << 7))
        dc = Discovery(c, _enr(c))
        for d in (da, db, dc):
            await d.start()
        try:
            await db.bootstrap([da.local_enr])
            await dc.bootstrap([da.local_enr])
            await da.lookup(da.local_enr.node_id)
            peers = da.find_peers_for_subnet(7)
            assert [e.node_id for e in peers] == [db.local_enr.node_id]
            # dc starts advertising subnet 7; its re-ping updates da's table
            bits = [False] * 64
            bits[7] = True
            dc.update_attnets(bits)
            await dc.ping(da.local_enr)
            peers = {e.node_id for e in da.find_peers_for_subnet(7)}
            assert dc.local_enr.node_id in peers
        finally:
            for d in (da, db, dc):
                d.stop()

    run(main())


def test_discovered_callback_fires():
    async def main():
        a, b = _identity(30), _identity(31)
        da, db = Discovery(a, _enr(a)), Discovery(b, _enr(b))
        await da.start()
        await db.start()
        found = []
        da.on_discovered.append(lambda enr: found.append(enr.node_id))
        try:
            await db.bootstrap([da.local_enr])
            for _ in range(50):
                if found:
                    break
                await asyncio.sleep(0.02)
            assert db.local_enr.node_id in found
        finally:
            da.stop()
            db.stop()

    run(main())


def test_network_auto_dials_discovered_peers():
    """Full integration: nodes find each other via discovery and dial
    automatically — no manual connect() (reference: discv5 → PeerManager)."""
    from lodestar_tpu.network.network import Network

    from tests.test_network_live import _fresh_chain

    async def main():
        nets = []
        for i in range(3):
            config, types, chain = _fresh_chain()
            net = Network(
                config, types, chain,
                identity=NodeIdentity.from_seed(bytes([40 + i])),
                verify_signatures=False,
            )
            nets.append(net)
        try:
            await nets[0].start(discovery=True)
            boot = [nets[0].discovery.local_enr]
            for net in nets[1:]:
                await net.start(discovery=True, bootnodes=boot)
            # one lookup round spreads the ENRs; network heartbeats retry
            # dialing anything known-but-unconnected
            for n in nets:
                await n.discovery.lookup(n.peer_id)
            for _ in range(200):
                if all(len(n.transport.connections) >= 2 for n in nets):
                    break
                await asyncio.sleep(0.1)
            counts = [len(n.transport.connections) for n in nets]
            assert all(c >= 1 for c in counts), counts
            # at least the bootstrap hub is fully connected
            assert len(nets[0].transport.connections) == 2
        finally:
            for net in nets:
                await net.stop()

    # dial backoff is 5-10 s/retry; under suite load convergence can
    # exceed the shared 60 s run() budget — give this one more headroom
    asyncio.run(asyncio.wait_for(main(), 180.0))


def test_findnode_requires_endpoint_proof():
    """Round-1 advisor low: FINDNODE from an unproven source address gets
    NO NODES response (anti-reflection) — only a PING to start the proof;
    after the round trip completes, queries are answered."""

    async def main():
        ia, ib = _identity(90), _identity(91)
        da, db = Discovery(ia, _enr(ia)), Discovery(ib, _enr(ib))
        await da.start()
        await db.start()
        try:
            await da.bootstrap([db.local_enr])  # ping: da proves itself to db
            assert db._endpoint_proven  # round trip completed
            db._endpoint_proven.clear()  # simulate an unproven source
            # the query is HELD through the challenge round-trip and then
            # answered — one extra RTT, no lost lookup
            enrs = await da.find_node(db.local_enr, da.local_enr.node_id)
            assert enrs
            assert db._endpoint_proven  # proof recorded by the PONG
        finally:
            da.stop()
            db.stop()

    asyncio.run(asyncio.wait_for(main(), 30))
