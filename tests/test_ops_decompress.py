"""Differential tests: device G2 decompression + batched subgroup check
vs the Python oracle (VERDICT r4 #5 — the device path that removes the
host marshal floor).

COMPILE DISCIPLINE: `decompress`/`fp2_sqrt` embed two 380-step pow
scans; every distinct batch shape is a fresh multi-minute CPU compile.
All tests here share ONE batch shape (8 lanes, padded) so the whole file
costs two compiles total.
"""

import numpy as np
import pytest

from lodestar_tpu.bls import api as bls

# deep-kernel compiles (~13 min cold on the CPU backend): slow tier
pytestmark = pytest.mark.slow
from lodestar_tpu.bls.curve import B2, PointG2, g2_from_bytes
from lodestar_tpu.bls.fields import P, Fq2
from lodestar_tpu.ops import fp2
from lodestar_tpu.ops import g2_decompress as D

# jit everything once per shape — eager execution compiles every op
# separately (hundreds of CPU compiles)
import jax

decompress = jax.jit(D.decompress)
fp2_sqrt = jax.jit(D.fp2_sqrt)
g2_mul_x_abs = jax.jit(D.g2_mul_x_abs)
planes_in_subgroup = jax.jit(D.planes_in_subgroup)
from lodestar_tpu.ops.io_host import fq2_to_limbs, g2_affine_to_limbs, limbs_to_fq2

LANES = 8


def _sig(i, msg):
    sig = bls.interop_secret_key(i).sign(msg)
    return np.frombuffer(sig.to_bytes(), np.uint8), sig.point


def _non_subgroup_point():
    x = Fq2.from_ints(5, 1)
    while True:
        y2 = x * x * x + B2
        y = y2.sqrt()
        if y is not None:
            pt = PointG2(x, y, Fq2.one())
            if not pt.is_in_subgroup():
                return pt
        x = x + Fq2.from_ints(1, 0)


def test_fp2_sqrt_differential_and_nonsquare():
    rng = np.random.default_rng(42)
    vals, expect_ok, squares = [], [], []
    for _ in range(LANES - 1):
        a = Fq2.from_ints(
            int(rng.integers(1 << 62)) * int(rng.integers(1 << 62)),
            int(rng.integers(1 << 62)),
        )
        sq = a * a
        vals.append(fq2_to_limbs(sq))
        expect_ok.append(True)
        squares.append(sq)
    # last lane: a non-square (square times the non-residue ξ = 1+u)
    xi = Fq2.from_ints(1, 1)
    ns = squares[0] * xi
    if ns.sqrt() is not None:
        ns = ns * xi
    assert ns.sqrt() is None
    vals.append(fq2_to_limbs(ns))
    expect_ok.append(False)

    y, ok = fp2_sqrt(np.stack(vals))
    assert list(np.asarray(ok)) == expect_ok
    for i, sq in enumerate(squares):
        got = limbs_to_fq2(np.asarray(y)[i])
        assert got * got == sq


def test_decompress_differential_all_cases():
    """One 8-lane dispatch: 3 valid sigs, a flipped sign flag, a cleared
    compression flag, the infinity encoding, x_c1 >= p, and an off-curve
    x — verdicts and coordinates all checked against the oracle."""
    raws, points = [], []
    for i in range(3):
        raw, pt = _sig(i, bytes([i]) * 32)
        raws.append(raw)
        points.append(pt)

    base, base_pt = _sig(3, b"\x77" * 32)
    flipped = base.copy()
    flipped[0] ^= 0x20  # sign flag → the other root
    raws.append(flipped)

    uncomp = base.copy()
    uncomp[0] &= 0x7F  # compression flag cleared
    raws.append(uncomp)

    raws.append(
        np.frombuffer(bytes([0xC0]) + b"\x00" * 95, np.uint8)  # infinity
    )

    over = base.copy()
    pb = np.frombuffer(P.to_bytes(48, "big"), np.uint8).copy()
    pb[0] |= 0x80 | (base[0] & 0x20)  # x_c1 = p with flags preserved
    over[:48] = pb
    raws.append(over)

    offcurve = base.copy()
    while True:
        offcurve[95] = np.uint8((int(offcurve[95]) + 1) % 256)
        try:
            g2_from_bytes(bytes(offcurve.tobytes()))
        except Exception:
            break
    raws.append(offcurve)

    x, y, ok = decompress(np.stack(raws))
    ok = np.asarray(ok)
    assert list(ok) == [True, True, True, True, False, False, False, False]
    for i, pt in enumerate(points):
        ax, ay = pt.to_affine()
        assert limbs_to_fq2(np.asarray(x)[i]) == ax
        assert limbs_to_fq2(np.asarray(y)[i]) == ay
    # the sign-flipped lane must give the NEGATED y of its source point
    _, ay = base_pt.to_affine()
    assert limbs_to_fq2(np.asarray(y)[3]) == -ay


def test_planes_subgroup_check_and_mul_x():
    """8 planes: G2 points pass; one non-subgroup component fails; the
    [|x|] ladder matches the oracle on a generic curve point."""
    from lodestar_tpu.bls.fields import X_PARAM

    pts = [
        bls.interop_secret_key(i).sign(bytes([i]) * 32).point
        for i in range(LANES)
    ]
    xs, ys = zip(*((g2_affine_to_limbs(p)[0], g2_affine_to_limbs(p)[1]) for p in pts))
    xs, ys = list(xs), list(ys)
    ones = np.asarray(fp2.one((LANES,)))
    assert bool(np.asarray(planes_in_subgroup((np.stack(xs), np.stack(ys), ones))))

    bad = _non_subgroup_point()
    bx, by, _ = g2_affine_to_limbs(bad)
    xs[5], ys[5] = bx, by
    assert not bool(
        np.asarray(planes_in_subgroup((np.stack(xs), np.stack(ys), ones)))
    )

    # [|x|]·P differential on the same (8,) shape (bad point in lane 0)
    got = g2_mul_x_abs((np.stack([bx] * LANES), np.stack([by] * LANES), ones))
    exp = (bad * abs(X_PARAM)).to_affine()
    zinv = limbs_to_fq2(np.asarray(got[2])[0]).inverse()
    assert limbs_to_fq2(np.asarray(got[0])[0]) * zinv == exp[0]
    assert limbs_to_fq2(np.asarray(got[1])[0]) * zinv == exp[1]

    # infinity planes pass (empty masks say nothing) — same shape again
    from lodestar_tpu.ops.points import g2 as g2ops

    inf = tuple(np.asarray(c) for c in g2ops.infinity((LANES,)))
    assert bool(np.asarray(planes_in_subgroup(inf)))
