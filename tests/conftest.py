"""Test harness setup.

Tests run on a virtual 8-device CPU mesh (no TPU needed): the env vars must be
set before jax initializes its backends. Multi-chip sharding paths are
exercised against this mesh; the driver's `dryrun_multichip` does the same.
"""

import os

import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Force CPU: the ambient environment may point JAX_PLATFORMS at a real TPU
# tunnel (single chip) — tests must not contend with the bench/driver for it,
# and a leaked device claim would hang backend init indefinitely.
# Set LODESTAR_TPU_TEST_PLATFORM=axon to run the suite on real hardware.
from lodestar_tpu.utils.jax_env import force_platform  # noqa: E402

_platform = os.environ.get("LODESTAR_TPU_TEST_PLATFORM", "cpu")
force_platform(_platform, 8 if _platform == "cpu" else None)

import jax  # noqa: E402

# Persistent compilation cache: the pairing/verifier kernels are deep
# (Miller-loop scans + final-exponentiation chains) and take minutes to
# compile on the CPU backend; caching makes repeat suite runs cheap.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

# AOT executable store (ISSUE 19): hermetic per-run directory unless the
# caller pins one — the suite must neither load a developer's repo-local
# .aot_store (stale executables would mask compile-path regressions) nor
# have tiny-budget prune tests delete its artifacts.
if "LODESTAR_TPU_AOT_STORE" not in os.environ:
    import tempfile

    os.environ["LODESTAR_TPU_AOT_STORE"] = tempfile.mkdtemp(
        prefix="lodestar_aot_test_"
    )

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_mesh():
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devices = np.array(jax.devices("cpu")[:8])
    return Mesh(devices.reshape(8), axis_names=("dp",))
