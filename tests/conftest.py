"""Test harness setup.

Tests run on a virtual 8-device CPU mesh (no TPU needed): the env vars must be
set before jax initializes its backends. Multi-chip sharding paths are
exercised against this mesh; the driver's `dryrun_multichip` does the same.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_mesh():
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devices = np.array(jax.devices("cpu")[:8])
    return Mesh(devices.reshape(8), axis_names=("dp",))
