"""Test harness setup.

Tests run on a virtual 8-device CPU mesh (no TPU needed): the env vars must be
set before jax initializes its backends. Multi-chip sharding paths are
exercised against this mesh; the driver's `dryrun_multichip` does the same.
"""

import os

# Force CPU: the ambient environment may point JAX_PLATFORMS at a real TPU
# tunnel (single chip) — tests must not contend with the bench/driver for it,
# and a leaked device claim would hang backend init indefinitely.
# Set LODESTAR_TPU_TEST_PLATFORM=axon to run the suite on real hardware.
_platform = os.environ.get("LODESTAR_TPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform

# A site hook may have imported jax at interpreter start, latching the
# ambient JAX_PLATFORMS (e.g. a tunnel-backed TPU plugin whose lazy client
# creation blocks on a single-device claim). Updating the live config — not
# just the env var — makes backends() initialize only the selected platform.
import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

# Persistent compilation cache: the pairing/verifier kernels are deep
# (Miller-loop scans + final-exponentiation chains) and take minutes to
# compile on the CPU backend; caching makes repeat suite runs cheap.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_mesh():
    import jax
    from jax.sharding import Mesh
    import numpy as np

    devices = np.array(jax.devices("cpu")[:8])
    return Mesh(devices.reshape(8), axis_names=("dp",))
